"""Fleet-telemetry layer (utils/metrics.py + its wiring).

Covers the PR-10 acceptance surface:

  - registry concurrency: multi-thread increments are EXACT (one lock,
    no lost updates — the same class of bug symlint C202 hunts);
  - exposition-format golden test: render_prometheus output is pinned
    byte-for-byte (a scrape consumer parses this text; drift is a
    silently-broken dashboard);
  - SLO burn-rate monitor: multiwindow semantics, rate limiting, and
    the deterministic fake-clock path driving a FlightRecorder dump;
  - wire-op round-trip: the HostOp.METRICS probe reply parses and
    merges tier-labeled through the backend;
  - disabled-mode overhead guard: a disabled registry costs one branch
    per call site — cheap enough that the echo path's handful of sites
    stays under 1% of a 1 ms chunk budget.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from symmetry_tpu.utils.metrics import (
    METRICS,
    LATENCY_BUCKETS,
    MetricName,
    MetricsRegistry,
    MetricsServer,
    SloMonitor,
    histogram_quantile,
    parse_prometheus_text,
    render_prometheus,
)


# ------------------------------------------------------------- registry


class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        r = MetricsRegistry()
        c = r.counter("t_req_total", "requests")
        c.inc()
        c.inc(3)
        assert c.value() == 4
        g = r.gauge("t_depth", "depth")
        g.set(7)
        g.add(-2)
        assert g.value() == 5
        h = r.histogram("t_lat_seconds", "latency")
        h.observe(0.002)
        h.observe(3.0)
        snap = r.snapshot()
        fam = snap["families"]["t_lat_seconds"]
        (s,) = fam["series"]
        assert s["count"] == 2
        assert s["sum"] == pytest.approx(3.002)
        assert s["min"] == 0.002 and s["max"] == 3.0
        # cumulative buckets end at the total count under +Inf
        assert s["buckets"][-1] == ["+Inf", 2]

    def test_labels_partition_series(self):
        r = MetricsRegistry()
        c = r.counter("t_shed_total", "sheds", labels=("reason",))
        c.inc(reason="busy")
        c.inc(2, reason="expired")
        assert c.value(reason="busy") == 1
        assert c.value(reason="expired") == 2
        assert c.value(reason="nope") == 0

    def test_reregistration_is_idempotent_but_kind_pinned(self):
        r = MetricsRegistry()
        r.counter("t_x_total", "x")
        r.counter("t_x_total")  # same kind+labels: fine
        with pytest.raises(ValueError):
            r.gauge("t_x_total")
        with pytest.raises(ValueError):
            r.counter("t_x_total", labels=("k",))

    def test_unlabeled_counters_materialize_at_zero(self):
        # A registered family must be visible from the first scrape —
        # an empty counter is a statement, a missing one is a question.
        r = MetricsRegistry()
        r.counter("t_zero_total", "never incremented")
        text = render_prometheus([{"snapshot": r.snapshot(), "labels": {}}])
        assert "t_zero_total 0" in text

    def test_multithread_increment_exactness(self):
        r = MetricsRegistry()
        c = r.counter("t_mt_total", "hammered", labels=("k",))
        h = r.histogram("t_mt_seconds", "hammered")
        n, threads = 2000, 8

        def hammer(i: int) -> None:
            for _ in range(n):
                c.inc(k="a")
                c.inc(0.5, k=f"t{i}")
                h.observe(0.01)

        ts = [threading.Thread(target=hammer, args=(i,))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value(k="a") == n * threads
        for i in range(threads):
            assert c.value(k=f"t{i}") == n * 0.5
        snap = r.snapshot()
        (s,) = snap["families"]["t_mt_seconds"]["series"]
        assert s["count"] == n * threads
        assert s["buckets"][-1][1] == n * threads

    def test_disabled_mode_is_inert_and_cheap(self):
        r = MetricsRegistry(enabled=False)
        c = r.counter("t_off_total", "off")
        h = r.histogram("t_off_seconds", "off")
        t0 = time.perf_counter()
        for _ in range(200_000):
            c.inc()
            h.observe(0.1)
        dt = time.perf_counter() - t0
        assert c.value() == 0
        (s,) = r.snapshot()["families"]["t_off_seconds"]["series"] \
            if r.snapshot()["families"]["t_off_seconds"]["series"] else [None]
        assert s is None or s["count"] == 0
        # 400k guarded ops; the bound is generous (CI shares cores) but
        # still pins the one-branch contract: ~100 ns/op measured, so a
        # chunk's ~5 sites stay far under 1% of a 1 ms chunk budget.
        assert dt < 1.0, f"disabled-mode: {dt:.3f}s for 400k guarded ops"
        per_op = dt / 400_000
        assert per_op * 5 < 0.01 * 1e-3

    def test_histogram_ring_is_bounded_time_series(self):
        r = MetricsRegistry()
        h = r.histogram("t_ring_seconds", "ring")
        for i in range(1000):
            h.observe(i * 1e-4)
        (s,) = r.snapshot()["families"]["t_ring_seconds"]["series"]
        from symmetry_tpu.utils.metrics import RING_CAPACITY

        assert len(s["recent"]) == RING_CAPACITY
        # compact drops the ring (the wire shape)
        (sc,) = r.snapshot(compact=True)[
            "families"]["t_ring_seconds"]["series"]
        assert "recent" not in sc
        assert sc["count"] == 1000


# ----------------------------------------------------------- exposition


GOLDEN = """\
# HELP g_req_total requests accepted
# TYPE g_req_total counter
g_req_total 3
# HELP g_shed_total sheds by reason
# TYPE g_shed_total counter
g_shed_total{reason="busy",tier="decode"} 2
# HELP g_lat_seconds latency
# TYPE g_lat_seconds histogram
g_lat_seconds_bucket{le="0.5"} 1
g_lat_seconds_bucket{le="5.0"} 2
g_lat_seconds_bucket{le="+Inf"} 2
g_lat_seconds_sum 1.1
g_lat_seconds_count 2
"""


class TestExposition:
    def test_render_golden(self):
        r = MetricsRegistry()
        r.counter("g_req_total", "requests accepted").inc(3)
        r.counter("g_shed_total", "sheds by reason",
                  labels=("reason", "tier")).inc(
                      2, reason="busy", tier="decode")
        h = r.histogram("g_lat_seconds", "latency", buckets=(0.5, 5.0))
        h.observe(0.1)
        h.observe(1.0)
        text = render_prometheus([{"snapshot": r.snapshot(), "labels": {}}])
        assert text == GOLDEN

    def test_extra_labels_stamp_every_series(self):
        r = MetricsRegistry()
        r.counter("g_x_total", "x").inc(1)
        text = render_prometheus(
            [{"snapshot": r.snapshot(), "labels": {"tier": "prefill"}}])
        assert 'g_x_total{tier="prefill"} 1' in text

    def test_parse_inverts_render(self):
        r = MetricsRegistry()
        r.counter("g_a_total", "a").inc(7)
        h = r.histogram("g_b_seconds", "b")
        h.observe(0.3)
        fams = parse_prometheus_text(render_prometheus(
            [{"snapshot": r.snapshot(), "labels": {"tier": "decode"}}]))
        assert fams["g_a_total"]["kind"] == "counter"
        (s,) = [s for s in fams["g_a_total"]["series"] if not s["suffix"]]
        assert s["value"] == 7 and s["labels"]["tier"] == "decode"
        count = [s for s in fams["g_b_seconds"]["series"]
                 if s["suffix"] == "_count"]
        assert count and count[0]["value"] == 1

    def test_label_escaping(self):
        r = MetricsRegistry()
        r.counter("g_esc_total", "esc", labels=("k",)).inc(
            k='we"ird\\nam\ne')
        text = render_prometheus([{"snapshot": r.snapshot(), "labels": {}}])
        assert '\\"' in text and "\\\\" in text and "\\n" in text
        assert "\nwe" not in text  # the raw newline never leaks

    def test_histogram_quantile_interpolates(self):
        # 100 samples uniform in le=1.0 bucket, none beyond.
        buckets = [(0.5, 0.0), (1.0, 100.0), ("+Inf", 100.0)]
        q50 = histogram_quantile(buckets, 0.50)
        assert 0.5 < q50 <= 1.0
        assert histogram_quantile([], 0.5) is None
        assert histogram_quantile([(0.5, 0.0), ("+Inf", 0.0)], 0.5) is None

    def test_http_server_serves_and_404s(self):
        import urllib.error
        import urllib.request

        srv = MetricsServer(lambda: "g_up 1\n", port=0)
        srv.start()
        try:
            url = f"http://127.0.0.1:{srv.port}"
            body = urllib.request.urlopen(f"{url}/metrics").read()
            assert body == b"g_up 1\n"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{url}/nope")
            assert ei.value.code == 404
        finally:
            srv.stop()


# ---------------------------------------------------------- SLO monitor


class FakeClock:
    def __init__(self) -> None:
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t


def make_monitor(clock, breaches, **over):
    cfg = {"ttft_s": 1.0, "objective": 0.99, "fast_window_s": 60.0,
           "slow_window_s": 600.0, "burn_threshold": 10.0,
           "min_interval_s": 0.0, **over}
    return SloMonitor(cfg, clock=clock, on_breach=breaches.append)


class TestSloMonitor:
    def test_good_events_never_breach(self):
        clock, breaches = FakeClock(), []
        m = make_monitor(clock, breaches)
        for _ in range(100):
            clock.t += 0.5
            assert m.observe("ttft", 0.2) is None
        assert breaches == []

    def test_sustained_burn_breaches_both_windows(self):
        clock, breaches = FakeClock(), []
        m = make_monitor(clock, breaches)
        for _ in range(20):
            clock.t += 1.0
            m.observe("ttft", 5.0)  # every event over target
        assert breaches, "sustained 100x burn never breached"
        ev = breaches[0]
        assert ev["slo"] == "ttft"
        assert ev["burn_fast"] >= 10 and ev["burn_slow"] >= 10

    def test_fast_burst_alone_does_not_breach_slow_window(self):
        clock, breaches = FakeClock(), []
        # Slow window holds a long good history; a short burst tips the
        # fast window but not the slow one — the multiwindow guard.
        m = make_monitor(clock, breaches, fast_window_s=10.0,
                         slow_window_s=600.0, burn_threshold=50.0)
        for _ in range(500):
            clock.t += 1.0
            m.observe("ttft", 0.1)  # good history
        for _ in range(5):
            clock.t += 1.0
            m.observe("ttft", 9.0)  # bad burst
        assert breaches == []

    def test_cold_start_single_bad_request_does_not_page(self):
        # Right after startup both windows hold the SAME few events; the
        # min_samples floor keeps one slow cold-start request (100x
        # burn over a one-sample window) from paging a healthy fleet.
        clock, breaches = FakeClock(), []
        m = make_monitor(clock, breaches)  # default min_samples=12
        clock.t += 1.0
        assert m.observe("ttft", 30.0) is None
        assert breaches == []
        # …and a floor of 1 restores the old behavior for tests/smokes
        m1 = make_monitor(clock, breaches, min_samples=1)
        clock.t += 1.0
        assert m1.observe("ttft", 30.0) is not None

    def test_rate_limit_between_breaches(self):
        clock, breaches = FakeClock(), []
        m = make_monitor(clock, breaches, min_interval_s=300.0)
        for _ in range(50):
            clock.t += 1.0
            m.observe("ttft", 5.0)
        assert len(breaches) == 1  # 50 burning observes, one page
        clock.t += 301.0
        m.observe("ttft", 5.0)
        assert len(breaches) == 2

    def test_burn_rate_accessor_feeds_pool_gauges(self):
        """SloMonitor.burn_rate(): the live fast-window burn the
        tpu_native pool heartbeat feeds into PoolRouter.update_gauges —
        0 while healthy, > 0 under burn, decaying as the window prunes,
        and 0 with no SLO configured."""
        clock, breaches = FakeClock(), []
        m = make_monitor(clock, breaches)
        assert m.burn_rate() == 0.0
        for _ in range(10):
            clock.t += 1.0
            m.observe("ttft", 5.0)  # every event over target
        burn = m.burn_rate()
        assert burn >= 10.0
        # the router consumes it through update_gauges and the member's
        # placement score reflects it
        from symmetry_tpu.engine.disagg.pool import PoolRouter

        router = PoolRouter()
        router.add_member("d0", "decode")
        router.mark_healthy("d0")
        router.update_gauges("d0", queue_depth=0, burn_rate=burn)
        (member,) = router.members("decode")
        assert member.burn_rate == pytest.approx(burn)
        # window prune: far in the future the burn decays to zero
        clock.t += 10_000.0
        assert m.burn_rate() == 0.0
        assert SloMonitor(None, clock=clock).burn_rate() == 0.0

    def test_unknown_slo_and_disabled_config(self):
        clock, breaches = FakeClock(), []
        m = make_monitor(clock, breaches)
        assert m.observe("nope", 9.0) is None
        off = SloMonitor(None, clock=clock)
        assert not off.enabled
        assert off.observe("ttft", 9.0) is None

    def test_burn_gauges_exported(self):
        clock, breaches = FakeClock(), []
        m = make_monitor(clock, breaches)
        clock.t += 1.0
        m.observe("ttft", 5.0)
        g = METRICS.gauge(MetricName.SLO_BURN_RATE,
                          labels=("slo", "window"))
        assert g.value(slo="ttft", window="fast") > 0

    def test_breach_drives_flight_recorder_deterministically(self, tmp_path):
        """The acceptance-criteria chain: fake clock → burn → breach →
        FlightRecorder.dump, no wall-clock sleeps anywhere."""
        from symmetry_tpu.utils.trace import FlightRecorder

        clock, dumps = FakeClock(), []
        fr = FlightRecorder(str(tmp_path), min_interval_s=0.0)

        def on_breach(event):
            dumps.append(fr.dump(f"slo_burn_{event['slo']}", [],
                                 stats={"burn": event["burn_fast"]}))

        m = SloMonitor({"ttft_s": 0.5, "objective": 0.99,
                        "fast_window_s": 60.0, "slow_window_s": 600.0,
                        "burn_threshold": 10.0, "min_interval_s": 0.0},
                       clock=clock, on_breach=on_breach)
        for _ in range(20):
            clock.t += 1.0
            m.observe("ttft", 2.0)
        assert dumps, "breach never dumped"
        with open(dumps[0], encoding="utf-8") as fh:
            payload = json.load(fh)
        assert payload["reason"] == "slo_burn_ttft"
        assert payload["stats"]["burn"] >= 10


# ------------------------------------------------------ wire round-trip


class TestMetricsWireOp:
    def test_host_metrics_reply_parses(self, capsys):
        from symmetry_tpu.engine.host import EngineHost
        from symmetry_tpu.protocol.keys import HostOp

        host = EngineHost(config=None)
        host._m_pipe_bytes.inc(0)  # ensure at least the host families exist
        host._handle_metrics()
        frame = json.loads(capsys.readouterr().out.strip())
        assert frame["op"] == HostOp.METRICS
        assert frame["role"] == "unified"
        assert MetricName.HOST_PIPE_WRITES in frame["families"]
        # the reply itself was one pipe write — counted
        fam = frame["families"][MetricName.HOST_PIPE_WRITES]
        assert not fam["series"] or fam["series"][0]["value"] >= 0

    def test_backend_merge_is_tier_labeled(self):
        import asyncio

        from symmetry_tpu.provider.backends.tpu_native import (
            TpuNativeBackend)
        from symmetry_tpu.provider.config import ConfigManager

        cfg = ConfigManager(config={
            "name": "t", "public": False, "serverKey": "00" * 32,
            "modelName": "m", "apiProvider": "tpu_native",
            "tpu": {"role": "disagg"}})
        be = TpuNativeBackend(cfg)
        decode_snap = {"op": "metrics", "role": "decode", "t_mono": 1.0,
                       "enabled": True, "families": {"f": {
                           "kind": "counter", "help": "", "labels": [],
                           "series": [{"labels": {}, "value": 2}]}}}
        prefill_snap = {**decode_snap, "role": "prefill"}

        async def probe_decode(timeout=10.0):
            return dict(decode_snap)

        async def probe_prefill(timeout=10.0):
            return dict(prefill_snap)

        be._probe_host_metrics = probe_decode
        be._probe_prefill_metrics = probe_prefill
        be._proc = type("P", (), {"returncode": None})()
        be._prefill_proc = type("P", (), {"returncode": None})()
        snaps = asyncio.new_event_loop().run_until_complete(
            be.metrics_snapshots())
        tiers = [s["labels"]["tier"] for s in snaps]
        assert tiers == ["decode", "prefill"]
        assert all("op" not in s["snapshot"] for s in snaps)
        # the merged exposition carries the tier labels through
        text = render_prometheus(snaps)
        assert 'f{tier="decode"} 2' in text
        assert 'f{tier="prefill"} 2' in text


# ----------------------------------------------------- structured logs


class TestLoggingFields:
    def test_json_records_carry_t_mono_and_component(self, capsys):
        from symmetry_tpu.utils.logging import (log_context, logger,
                                                set_component)

        logger.set_json_mode(True)
        try:
            set_component("testproc")
            with log_context(trace_id="tr", component="slo"):
                logger.warning("burn")
            logger.info("plain")
        finally:
            logger.set_json_mode(False)
            set_component("")
        lines = [json.loads(line) for line in
                 capsys.readouterr().err.strip().splitlines()]
        assert lines[0]["component"] == "slo"       # context overrides
        assert lines[0]["trace_id"] == "tr"
        assert isinstance(lines[0]["t_mono"], float)
        assert lines[1]["component"] == "testproc"  # process default
        assert lines[0]["t_mono"] <= lines[1]["t_mono"]


# --------------------------------------------------------------- symtop


class TestSymtop:
    def test_rows_and_table_from_snapshots(self):
        import tools.symtop as symtop

        r = MetricsRegistry()
        r.counter(MetricName.PROVIDER_TOKENS_OUT, "t").inc(500)
        r.gauge(MetricName.PROVIDER_UPTIME, "u").set(10.0)
        r.gauge(MetricName.PROVIDER_IN_FLIGHT, "i").set(3)
        r.histogram(MetricName.PROVIDER_TTFT, "h",
                    buckets=LATENCY_BUCKETS).observe(0.2)
        sched = MetricsRegistry()
        sched.gauge(MetricName.SCHED_OCCUPANCY, "o").set(5)
        sched.gauge(MetricName.SCHED_QUEUE_DEPTH, "q").set(2)
        sched.histogram(MetricName.SCHED_TTFT, "t",
                        buckets=LATENCY_BUCKETS).observe(4.0)
        fams = symtop.families_from_snapshots([
            {"snapshot": r.snapshot(compact=True), "labels": {}},
            {"snapshot": sched.snapshot(compact=True),
             "labels": {"tier": "decode"}},
        ])
        rows = symtop.build_rows("prov-a", fams, None, now=0.0)
        assert rows[0]["tok_s"] == pytest.approx(50.0)
        assert rows[0]["in_flight"] == 3
        assert rows[0]["ttft_p50"] is not None
        assert rows[1]["tier"] == "decode"
        assert rows[1]["occupancy"] == 5 and rows[1]["queue"] == 2
        # tier TTFT is the ENGINE-side enqueue→first-token latency —
        # queue wait shows under overload, unlike dispatch wall
        assert rows[1]["ttft_p99"] == pytest.approx(4.0, abs=2.0)
        rows[0].pop("_sample", None)
        table = symtop.render_table(rows)
        assert "prov-a" in table and "decode" in table

    def test_gap_and_depth_columns(self):
        """Tier sub-rows carry the dispatch-gap share (rendered as a
        percentage) and the live pipeline depth — the two numbers the
        overlapped scheduler is judged by, readable off the live table."""
        import tools.symtop as symtop

        r = MetricsRegistry()
        r.counter(MetricName.PROVIDER_TOKENS_OUT, "t").inc(100)
        r.gauge(MetricName.PROVIDER_UPTIME, "u").set(10.0)
        sched = MetricsRegistry()
        sched.gauge(MetricName.SCHED_OCCUPANCY, "o").set(2)
        sched.gauge(MetricName.DISPATCH_GAP_SHARE, "g").set(0.07)
        sched.gauge(MetricName.SCHED_PIPELINE_DEPTH, "d").set(2)
        fams = symtop.families_from_snapshots([
            {"snapshot": r.snapshot(compact=True), "labels": {}},
            {"snapshot": sched.snapshot(compact=True),
             "labels": {"tier": "decode"}},
        ])
        rows = symtop.build_rows("prov-a", fams, None, now=0.0)
        assert rows[0].get("gap") is None       # provider row: engine-only
        tier = rows[1]
        assert tier["gap"] == "7%"
        assert tier["depth"] == 2
        rows[0].pop("_sample", None)
        table = symtop.render_table(rows)
        header = table.splitlines()[0]
        assert "GAP%" in header and "DEPTH" in header
        assert "7%" in table

    def test_rate_from_previous_sample(self):
        import tools.symtop as symtop

        r = MetricsRegistry()
        r.counter(MetricName.PROVIDER_TOKENS_OUT, "t").inc(1000)
        r.counter(MetricName.PROVIDER_SHEDS, "s",
                  labels=("reason",)).inc(30, reason="busy")
        fams = symtop.families_from_snapshots(
            [{"snapshot": r.snapshot(compact=True), "labels": {}}])
        rows = symtop.build_rows(
            "p", fams, {"t": 0.0, "tok": 800.0, "shed": 20.0}, now=2.0)
        assert rows[0]["tok_s"] == pytest.approx(100.0)
        # shed is a RATE between polls, not the lifetime total
        assert rows[0]["shed"] == pytest.approx(5.0)

    def test_target_and_scale_columns(self):
        """Autoscaled pools surface TARGET (live MxN vs the
        controller's desired MxN) and SCALE (booked decisions/minute)
        on the provider row; non-autoscaled providers show dashes."""
        import tools.symtop as symtop

        r = MetricsRegistry()
        r.counter(MetricName.PROVIDER_TOKENS_OUT, "t").inc(100)
        r.gauge(MetricName.PROVIDER_UPTIME, "u").set(10.0)
        tgt = r.gauge(MetricName.AUTOSCALE_TARGET, "tm",
                      labels=("tier",))
        tgt.set(2, tier="prefill")
        tgt.set(1, tier="decode")
        r.counter(MetricName.AUTOSCALE_DECISIONS, "d",
                  labels=("action", "tier")).inc(
                      3, action="spawn", tier="prefill")
        st = r.gauge(MetricName.POOL_MEMBER_STATE, "s",
                     labels=("tier", "node"))
        st.set(1, tier="prefill", node="prefill-0")  # healthy
        st.set(1, tier="decode", node="decode-0")
        fams = symtop.families_from_snapshots(
            [{"snapshot": r.snapshot(compact=True), "labels": {}}])
        rows = symtop.build_rows("p", fams, None, now=0.0)
        # live 1x1 still converging toward the desired 2x1
        assert rows[0]["target"] == "1x1>2x1"
        assert rows[0]["scale"] == 3  # first poll: lifetime total
        rows2 = symtop.build_rows(
            "p", fams, {"t": 0.0, "tok": 0.0, "shed": 0.0, "dec": 1.0},
            now=30.0)
        assert rows2[0]["scale"] == pytest.approx(4.0)  # 2 in 30s /min
        rows[0].pop("_sample", None)
        table = symtop.render_table(rows)
        header = table.splitlines()[0]
        assert "TARGET" in header and "SCALE" in header
        assert "1x1>2x1" in table
        # steady state collapses to one MxN; no autoscaler → dashes
        st.set(1, tier="prefill", node="prefill-1")
        fams = symtop.families_from_snapshots(
            [{"snapshot": r.snapshot(compact=True), "labels": {}}])
        assert symtop.build_rows("p", fams, None,
                                 now=0.0)[0]["target"] == "2x1"
        bare = MetricsRegistry()
        bare.counter(MetricName.PROVIDER_TOKENS_OUT, "t").inc(1)
        fams = symtop.families_from_snapshots(
            [{"snapshot": bare.snapshot(compact=True), "labels": {}}])
        row = symtop.build_rows("p", fams, None, now=0.0)[0]
        assert row["target"] is None and row["scale"] is None


# ---------------------------------------- resume / pool family exposition


RESUME_POOL_GOLDEN = """\
# HELP sym_resume_requests_total resumes handled
# TYPE sym_resume_requests_total counter
sym_resume_requests_total{outcome="resumed"} 3
sym_resume_requests_total{outcome="refused"} 1
# HELP sym_resume_wasted_tokens_total overlap tokens dedup dropped
# TYPE sym_resume_wasted_tokens_total counter
sym_resume_wasted_tokens_total 17
# HELP sym_resume_reused_tokens_total radix tokens resumes reused
# TYPE sym_resume_reused_tokens_total counter
sym_resume_reused_tokens_total{tier="decode"} 96
# HELP sym_provider_flight_dumps_total flight-recorder dumps written
# TYPE sym_provider_flight_dumps_total counter
sym_provider_flight_dumps_total{reason="slo_burn_ttft"} 2
# HELP sym_pool_placements_total lifetime placements
# TYPE sym_pool_placements_total counter
sym_pool_placements_total{node="p0",tier="prefill"} 5
sym_pool_placements_total{node="p1",tier="prefill"} 3
# HELP sym_pool_member_state membership state code
# TYPE sym_pool_member_state gauge
sym_pool_member_state{node="p0",tier="prefill"} 1
sym_pool_member_state{node="p1",tier="prefill"} 3
"""


class TestResumePoolExposition:
    """PR-15 satellite: the PR-11/14 families symtop now renders get the
    same golden-exposition + parse-round-trip coverage the PR-10
    scheduler/provider families have — a format drift in THESE names is
    a silently-empty RESUME/DUMPS/STATE column, not an error."""

    def _registry(self) -> MetricsRegistry:
        r = MetricsRegistry()
        r.counter(MetricName.PROVIDER_RESUMES, "resumes handled",
                  labels=("outcome",)).inc(3, outcome="resumed")
        r.counter(MetricName.PROVIDER_RESUMES, "resumes handled",
                  labels=("outcome",)).inc(1, outcome="refused")
        r.counter(MetricName.RESUME_WASTED_TOKENS,
                  "overlap tokens dedup dropped").inc(17)
        r.counter(MetricName.SCHED_RESUME_REUSED,
                  "radix tokens resumes reused",
                  labels=("tier",)).inc(96, tier="decode")
        r.counter(MetricName.PROVIDER_FLIGHT_DUMPS,
                  "flight-recorder dumps written",
                  labels=("reason",)).inc(2, reason="slo_burn_ttft")
        pool = r.counter(MetricName.POOL_PLACEMENTS, "lifetime placements",
                         labels=("tier", "node"))
        pool.inc(5, tier="prefill", node="p0")
        pool.inc(3, tier="prefill", node="p1")
        state = r.gauge(MetricName.POOL_MEMBER_STATE,
                        "membership state code", labels=("tier", "node"))
        state.set(1, tier="prefill", node="p0")   # healthy
        state.set(3, tier="prefill", node="p1")   # lost
        return r

    def test_resume_pool_golden_exposition(self):
        text = render_prometheus(
            [{"snapshot": self._registry().snapshot(compact=True),
              "labels": {}}])
        assert text == RESUME_POOL_GOLDEN

    def test_resume_pool_parse_round_trip(self):
        r = self._registry()
        fams = parse_prometheus_text(render_prometheus(
            [{"snapshot": r.snapshot(compact=True), "labels": {}}]))
        res = fams[MetricName.PROVIDER_RESUMES]
        assert res["kind"] == "counter"
        assert {s["labels"]["outcome"]: s["value"]
                for s in res["series"]} == {"resumed": 3.0, "refused": 1.0}
        (wasted,) = fams[MetricName.RESUME_WASTED_TOKENS]["series"]
        assert wasted["value"] == 17.0
        (reused,) = fams[MetricName.SCHED_RESUME_REUSED]["series"]
        assert reused["labels"]["tier"] == "decode"
        assert reused["value"] == 96.0
        dumps = fams[MetricName.PROVIDER_FLIGHT_DUMPS]["series"]
        assert dumps[0]["labels"]["reason"] == "slo_burn_ttft"
        states = {s["labels"]["node"]: s["value"]
                  for s in fams[MetricName.POOL_MEMBER_STATE]["series"]}
        assert states == {"p0": 1.0, "p1": 3.0}

    def test_symtop_resume_and_dump_columns(self):
        """The provider row shows resumes/wasted/dumps; tier sub-rows
        show resume admissions + reused tokens (the cheap-resume
        contract reads straight off the table)."""
        import tools.symtop as symtop

        r = self._registry()
        r.counter(MetricName.PROVIDER_TOKENS_OUT, "t").inc(100)
        r.gauge(MetricName.PROVIDER_UPTIME, "u").set(10.0)
        sched = MetricsRegistry()
        sched.gauge(MetricName.SCHED_OCCUPANCY, "o").set(1)
        sched.counter(MetricName.SCHED_RESUMES, "resume admissions").inc(2)
        sched.counter(MetricName.SCHED_RESUME_REUSED,
                      "reused").inc(96)
        fams = symtop.families_from_snapshots([
            {"snapshot": r.snapshot(compact=True), "labels": {}},
            {"snapshot": sched.snapshot(compact=True),
             "labels": {"tier": "decode"}},
        ])
        rows = symtop.build_rows("prov-a", fams, None, now=0.0)
        assert rows[0]["resume"] == 4.0      # resumed + refused
        assert rows[0]["wasted"] == 17.0
        assert rows[0]["dumps"] == 2.0
        tier = rows[1]
        assert tier["tier"] == "decode"
        assert tier["resume"] == 2.0
        assert tier["reused"] == 96.0 * 2    # registry + sched snapshots
        rows[0].pop("_sample", None)
        table = symtop.render_table(rows)
        header = table.splitlines()[0]
        for col in ("RESUME", "WASTED", "REUSED", "DUMPS"):
            assert col in header
        assert "17" in table and "prov-a" in table
