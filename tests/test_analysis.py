"""symlint analyzer suite tests (symmetry_tpu/analysis/, tools/symlint.py).

Per checker: a seeded true positive (the drift the checker exists to
catch) and a true negative (the idiomatic clean shape must not flag).
Plus: baseline suppression semantics, the runner's JSON schema and exit
codes (the CI gate is `exit != 0` on a seeded wire-op mismatch), and
the self-test — the repo itself must run clean modulo the justified
baseline, which is also the regression lock on the concurrency fixes
this suite originally surfaced (engine/host.py handoff/adopt stats).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import pytest

from symmetry_tpu.analysis import ALL_CHECKERS, Baseline, run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KEYS_PY = '''\
class HostOp:
    SUBMIT = "submit"
    EVENT = "event"
    EVENTS = "events"


class MessageKey:
    PING = "ping"
    PONG = "pong"
'''


def write_tree(root, files: dict[str, str]) -> str:
    for rel, src in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(src)
    return str(root)


def codes(findings) -> set[str]:
    return {f.code for f in findings}


# ------------------------------------------------------------ wire-contract


class TestWireContract:
    def test_mismatch_and_raw_literal_flag(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/protocol/keys.py": KEYS_PY,
            # producer emits a typo'd op, plus a registered op spelled
            # as a raw literal…
            "symmetry_tpu/engine/host.py": (
                'def emit(w):\n'
                '    w({"op": "evnt", "id": "r1"})\n'
                '    w({"op": "submit", "id": "r1"})\n'),
            # …while the consumer dispatches on the real one
            "symmetry_tpu/provider/backends/tpu_native.py": (
                'from symmetry_tpu.protocol.keys import HostOp\n'
                'def pump(msg):\n'
                '    op = msg.get("op")\n'
                '    if op == HostOp.EVENT:\n'
                '        return msg\n'),
        })
        fs = run(root)
        got = codes(fs)
        assert "W102" in got     # "evnt" produced, never consumed
        assert "W103" in got     # "event" consumed, never produced
        assert "W104" in got     # "evnt" unknown to HostOp
        assert "W101" in got     # raw literal in a registry'd group file
        syms = {f.symbol for f in fs if f.code == "W102"}
        assert syms == {"evnt", "submit"}  # both lack a consumer

    def test_clean_when_both_sides_use_constants(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/protocol/keys.py": KEYS_PY,
            "symmetry_tpu/engine/host.py": (
                'from symmetry_tpu.protocol.keys import HostOp\n'
                'def emit(w):\n'
                '    w({"op": HostOp.EVENT, "id": "r1"})\n'
                '    m = {}\n'
                '    m["op"] = HostOp.EVENTS\n'
                '    w(m)\n'),
            "symmetry_tpu/provider/backends/tpu_native.py": (
                'from symmetry_tpu.protocol.keys import HostOp\n'
                'def pump(msg):\n'
                '    op = msg.get("op")\n'
                '    if op in (HostOp.EVENT, HostOp.EVENTS):\n'
                '        return msg\n'),
        })
        assert run(root) == []

    def test_link_op_group_mismatch_and_raw_literal(self, tmp_path):
        """The handoff-link protocol (LinkOp, engine/disagg/net.py +
        node.py) gets the same W101–W104 discipline over its own group
        — and LinkOp's deliberate HostOp value reuse must NOT leak
        across registries (a LinkOp.X reference is invisible to the
        HostOp scan and vice versa)."""
        root = write_tree(tmp_path, {
            "symmetry_tpu/protocol/keys.py": (
                KEYS_PY + '\n\n'
                'class LinkOp:\n'
                '    SUBMIT = "submit"\n'
                '    BEGIN = "begin"\n'
                '    CHUNK = "chunk"\n'),
            # link producer: raw literal for a registered link op, plus
            # an op consumed nowhere in the link group
            "symmetry_tpu/engine/disagg/net.py": (
                'from symmetry_tpu.protocol.keys import LinkOp\n'
                'async def send(link):\n'
                '    await link.send({"op": "begin", "xfer": "x"})\n'
                '    await link.send({"op": LinkOp.CHUNK, "seq": 0})\n'),
            "symmetry_tpu/engine/disagg/node.py": (
                'from symmetry_tpu.protocol.keys import LinkOp\n'
                'def pump(header):\n'
                '    op = header.get("op")\n'
                '    if op == LinkOp.CHUNK:\n'
                '        return header\n'
                '    if op == LinkOp.SUBMIT:\n'
                '        return header\n'),
        })
        fs = [f for f in run(root) if f.checker == "wire-contract"]
        got = codes(fs)
        assert "W101" in got     # raw "begin" literal in the link group
        assert "W102" in got     # begin produced, never consumed
        assert "W103" in got     # submit consumed, never produced
        w103 = {f.symbol for f in fs if f.code == "W103"}
        # "submit" is unmatched in the LINK group even though HostOp
        # also registers the value — the registries do not cross-talk.
        assert "submit" in w103

    def test_link_op_group_clean_with_constants(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/protocol/keys.py": (
                KEYS_PY + '\n\n'
                'class LinkOp:\n'
                '    BEGIN = "begin"\n'),
            "symmetry_tpu/engine/disagg/net.py": (
                'from symmetry_tpu.protocol.keys import LinkOp\n'
                'async def send(link):\n'
                '    await link.send({"op": LinkOp.BEGIN})\n'),
            "symmetry_tpu/engine/disagg/node.py": (
                'from symmetry_tpu.protocol.keys import LinkOp\n'
                'def pump(header):\n'
                '    op = header.get("op")\n'
                '    if op == LinkOp.BEGIN:\n'
                '        return header\n'),
        })
        assert [f for f in run(root) if f.checker == "wire-contract"] \
            == []

    def test_real_link_registry_fully_covered(self):
        """Registry-coverage pin on the REAL repo: every LinkOp constant
        is BOTH produced (a `{"op": LinkOp.X}` dict display) and
        consumed (a compare/membership against LinkOp.X) somewhere in
        the link group — an op that loses either side fails here before
        it strands a handoff on the wire."""
        import ast

        from symmetry_tpu.protocol.keys import LINK_OPS, LinkOp

        def link_attrs(node):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) and \
                        isinstance(sub.value, ast.Name) and \
                        sub.value.id == "LinkOp":
                    yield sub.attr

        produced: set[str] = set()
        consumed: set[str] = set()
        for rel in ("symmetry_tpu/engine/disagg/net.py",
                    "symmetry_tpu/engine/disagg/node.py"):
            with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
            for node in ast.walk(tree):
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if (isinstance(k, ast.Constant)
                                and k.value == "op"):
                            produced.update(link_attrs(v))
                elif isinstance(node, ast.Compare):
                    consumed.update(link_attrs(node))
        names = {k for k in vars(LinkOp) if not k.startswith("_")}
        assert produced >= names, \
            f"LinkOp constants never produced: {names - produced}"
        assert consumed >= names, \
            f"LinkOp constants never consumed: {names - consumed}"
        assert len(LINK_OPS) == len(names), \
            "duplicate LinkOp values would alias wire ops"

    def test_nonexistent_registry_attribute_flags(self, tmp_path):
        # HostOp.EVNT (typo'd CONSTANT, not value) must flag, not vanish
        # from the consumed set: at runtime it is an AttributeError on a
        # rarely-taken dispatch arm.
        root = write_tree(tmp_path, {
            "symmetry_tpu/protocol/keys.py": KEYS_PY,
            "symmetry_tpu/engine/host.py": (
                'from symmetry_tpu.protocol.keys import HostOp\n'
                'def emit(w):\n'
                '    w({"op": HostOp.EVENT})\n'),
            "symmetry_tpu/provider/backends/tpu_native.py": (
                'from symmetry_tpu.protocol.keys import HostOp\n'
                'def pump(msg):\n'
                '    op = msg.get("op")\n'
                '    if op == HostOp.EVNT:\n'
                '        return msg\n'
                '    if op == HostOp.EVENT:\n'
                '        return msg\n'),
        })
        fs = run(root)
        w104 = [f for f in fs if f.code == "W104"]
        assert [f.symbol for f in w104] == ["HostOp.EVNT"]

    def test_subscript_consumer_shape_recognized(self, tmp_path):
        # `msg["op"] == HostOp.X` is a consumer too — missing it would
        # false-W102 the producer of a perfectly consumed op.
        root = write_tree(tmp_path, {
            "symmetry_tpu/protocol/keys.py": KEYS_PY,
            "symmetry_tpu/engine/host.py": (
                'from symmetry_tpu.protocol.keys import HostOp\n'
                'def emit(w):\n'
                '    w({"op": HostOp.EVENT})\n'),
            "symmetry_tpu/provider/backends/tpu_native.py": (
                'from symmetry_tpu.protocol.keys import HostOp\n'
                'def pump(msg):\n'
                '    if msg["op"] == HostOp.EVENT:\n'
                '        return msg\n'),
        })
        assert run(root) == []

    def test_message_key_send_without_handler(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/protocol/keys.py": KEYS_PY,
            "symmetry_tpu/provider/provider.py": (
                'from symmetry_tpu.protocol.keys import MessageKey\n'
                'async def serve(peer, msg):\n'
                '    if msg.key == MessageKey.PING:\n'
                '        await peer.send(MessageKey.PONG)\n'),
            # nobody handles pong, nobody sends ping
        })
        got = codes(run(root))
        assert "W106" in got and "W107" in got


# -------------------------------------------------------------- concurrency


class TestConcurrency:
    def test_blocking_call_in_async_flags(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/provider/p.py": (
                'import time\n'
                'async def relay():\n'
                '    time.sleep(1.0)\n'),
        })
        fs = run(root)
        assert codes(fs) == {"C201"}

    def test_async_sleep_and_executor_helper_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/provider/p.py": (
                'import asyncio, time\n'
                'async def relay():\n'
                '    await asyncio.sleep(1.0)\n'
                '    def build():\n'
                '        time.sleep(0.1)  # runs in a thread, allowed\n'
                '    await asyncio.to_thread(build)\n'),
        })
        assert run(root) == []

    def test_cross_thread_mutation_without_lock_flags(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import threading\n'
                'class Loop:\n'
                '    def __init__(self):\n'
                '        self.count = 0\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        self.count += 1\n'
                '    def submit(self):\n'
                '        self.count += 1\n'),
        })
        fs = run(root)
        assert codes(fs) == {"C202"}
        assert fs[0].symbol == "Loop.count"

    def test_locked_mutation_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import threading\n'
                'class Loop:\n'
                '    def __init__(self):\n'
                '        self.count = 0\n'
                '        self._lock = threading.Lock()\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        with self._lock:\n'
                '            self.count += 1\n'
                '    def submit(self):\n'
                '        with self._lock:\n'
                '            self.count += 1\n'),
        })
        assert run(root) == []

    def test_escaped_closure_counts_as_thread_context(self, tmp_path):
        # The exact shape of the engine-host adopt-thunk race this
        # checker caught for real: a local def handed to other
        # machinery mutates the same counter the pipe thread does.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/h.py": (
                'class Host:\n'
                '    def __init__(self, sched):\n'
                '        self.stats = {"errors": 0}\n'
                '        self._sched = sched\n'
                '    def handle(self, msg):\n'
                '        def adopt(req):\n'
                '            self.stats["errors"] += 1\n'
                '        self._sched.submit(adopt)\n'
                '        self.stats["errors"] += 1\n'),
        })
        fs = run(root)
        assert codes(fs) == {"C202"}
        assert "stats['errors']" in fs[0].symbol

    def test_different_locks_do_not_exclude(self, tmp_path):
        # Two sites each "locked" — but by DIFFERENT locks: still a race.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import threading\n'
                'class Loop:\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        with self._stats_lock:\n'
                '            self.count += 1\n'
                '    def submit(self):\n'
                '        with self._io_lock:\n'
                '            self.count += 1\n'),
        })
        fs = run(root)
        assert codes(fs) == {"C202"}
        # the diagnostic must not claim "unlocked" — both sites hold a
        # lock, just not the same one
        assert "no common lock" in fs[0].message

    def test_mutator_method_calls_are_mutations(self, tmp_path):
        # The .update()/.pop() shape of the same race class — invisible
        # to Assign/AugAssign extraction, so tracked explicitly.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import threading\n'
                'class Loop:\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        self.stats.update(done=1)\n'
                '    def submit(self, k):\n'
                '        self.stats.pop(k, None)\n'),
        })
        fs = run(root)
        assert codes(fs) == {"C202"}
        assert fs[0].symbol == "Loop.stats"

    def test_result_with_timeout_still_blocks(self, tmp_path):
        # Future.result(timeout=30) blocks the loop for up to 30 s —
        # the timeout kwarg must not exempt it.
        root = write_tree(tmp_path, {
            "symmetry_tpu/provider/p.py": (
                'async def relay(fut):\n'
                '    return fut.result(timeout=30)\n'),
        })
        assert codes(run(root)) == {"C201"}

    def test_whole_dict_mutator_collides_with_key_writes(self, tmp_path):
        # thread A rewrites the dict wholesale, thread B bumps one key:
        # different granularities, same race.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import threading\n'
                'class Loop:\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        self.stats.update(requests=0)\n'
                '    def submit(self):\n'
                '        self.stats["requests"] += 1\n'),
        })
        fs = run(root)
        assert codes(fs) == {"C202"}
        assert "stats['requests']" in fs[0].symbol

    def test_unbounded_cross_thread_queue_flags(self, tmp_path):
        # C203 TP: main-thread producers, worker-thread consumer, no
        # maxsize — the slow-consumer OOM shape.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import queue, threading\n'
                'class Loop:\n'
                '    def __init__(self):\n'
                '        self.inbox = queue.Queue()\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        while True:\n'
                '            self.inbox.get()\n'
                '    def submit(self, item):\n'
                '        self.inbox.put(item)\n'),
        })
        fs = run(root)
        assert codes(fs) == {"C203"}
        assert fs[0].symbol == "Loop.inbox"
        assert "unbounded" in fs[0].message

    def test_bounded_emit_queue_handoff_clean(self, tmp_path):
        # C203 TN: the scheduler's emit-worker shape — a bounded queue
        # (nonzero maxsize, even computed) between the dispatch thread
        # and the emit worker, sentinel None shutdown included. The
        # blocking put IS the designed backpressure; nothing to flag.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import queue, threading\n'
                'class Sched:\n'
                '    def __init__(self, emit_queue_blocks=8):\n'
                '        self._emit_queue = queue.Queue(\n'
                '            maxsize=max(1, int(emit_queue_blocks)))\n'
                '    def start(self):\n'
                '        threading.Thread(\n'
                '            target=self._emit_worker_run).start()\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        self._emit_queue.put(["job"])\n'
                '        self._emit_queue.put(None)\n'
                '    def _emit_worker_run(self):\n'
                '        while True:\n'
                '            jobs = self._emit_queue.get()\n'
                '            if jobs is None:\n'
                '                return\n'),
        })
        assert run(root) == []

    def test_single_thread_and_asyncio_queues_clean(self, tmp_path):
        # C203 TN ×2: an unbounded queue both produced and consumed by
        # the SAME worker thread (a private work list — no cross-thread
        # backlog), and an asyncio.Queue (loop-internal flow control,
        # out of scope for a thread checker).
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import queue, threading\n'
                'class Loop:\n'
                '    def __init__(self):\n'
                '        self.todo = queue.Queue()\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        self.todo.put(1)\n'
                '        self.todo.get()\n'),
            "symmetry_tpu/provider/p.py": (
                'import asyncio\n'
                'class Relay:\n'
                '    def __init__(self):\n'
                '        self.frames = asyncio.Queue()\n'
                '    def handle(self):\n'
                '        self.frames.put_nowait(b"x")\n'
                '    async def pump(self):\n'
                '        return await self.frames.get()\n'),
        })
        assert run(root) == []

    def test_simplequeue_cross_thread_flags(self, tmp_path):
        # SimpleQueue cannot be bounded at all — crossing threads, it
        # is always the C203 shape.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import queue, threading\n'
                'class Loop:\n'
                '    def __init__(self):\n'
                '        self.out = queue.SimpleQueue()\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        self.out.put(1)\n'
                '    def drain(self):\n'
                '        return self.out.get()\n'),
        })
        fs = run(root)
        assert codes(fs) == {"C203"}

    def test_nested_async_blocking_reported_once(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/network/n.py": (
                'import time\n'
                'async def dial():\n'
                '    async def burst():\n'
                '        time.sleep(0.1)\n'
                '    await burst()\n'),
        })
        fs = run(root)
        assert [f.symbol for f in fs] == ["burst:time.sleep"]

    def test_per_key_granularity_is_not_a_race(self, tmp_path):
        # engine thread owns metrics["steps"], callers own
        # metrics["requests"]: distinct GIL-atomic keys, no finding.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/s.py": (
                'import threading\n'
                'class Loop:\n'
                '    def __init__(self):\n'
                '        self.metrics = {"requests": 0, "steps": 0}\n'
                '    def start(self):\n'
                '        threading.Thread(target=self._run).start()\n'
                '    def _run(self):\n'
                '        self.metrics["steps"] += 1\n'
                '    def submit(self):\n'
                '        self.metrics["requests"] += 1\n'),
        })
        assert run(root) == []


# --------------------------------------------------------- recompile-hazard


class TestRecompileHazard:
    def test_value_branch_and_int_flag(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/ops/k.py": (
                'import functools, jax\n'
                '@functools.partial(jax.jit, static_argnames=("bk",))\n'
                'def f(x, n, bk):\n'
                '    if n > 0:\n'
                '        x = x + 1\n'
                '    m = int(n)\n'
                '    return x, m\n'),
        })
        got = codes(run(root))
        assert got == {"R301", "R302"}

    def test_shape_branch_and_static_arg_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/ops/k.py": (
                'import functools, jax\n'
                '@functools.partial(jax.jit, static_argnames=("bk",))\n'
                'def f(x, bk, w=None):\n'
                '    if x.shape[0] > 1 and bk > 8:\n'
                '        x = x * 2\n'
                '    if w is not None:\n'
                '        x = x + w\n'
                '    n = int(x.shape[1])\n'
                '    return x, n\n'),
        })
        assert run(root) == []

    def test_call_site_jit_wrapping_and_host_pull(self, tmp_path):
        # the engine's `self._p = jax.jit(prefill, donate_argnums=…)`
        # shape: the wrapped def is found by name, np.asarray flags
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/engine.py": (
                'import jax\n'
                'import numpy as np\n'
                'class E:\n'
                '    def build(self):\n'
                '        def prefill(tokens, params):\n'
                '            host = np.asarray(tokens)\n'
                '            return host\n'
                '        self._prefill = jax.jit(prefill,'
                ' donate_argnums=(0,))\n'),
        })
        fs = run(root)
        assert codes(fs) == {"R303"}
        assert fs[0].symbol.startswith("prefill:")

    def test_same_named_defs_are_each_analyzed(self, tmp_path):
        # Two builders each jit-wrap their own nested `def step`: a
        # name-keyed registry would analyze the first and silently
        # skip the hazard in the second.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/engine.py": (
                'import jax\n'
                'class A:\n'
                '    def build(self):\n'
                '        def step(x):\n'
                '            return x\n'
                '        self._s = jax.jit(step)\n'
                'class B:\n'
                '    def build(self):\n'
                '        def step(x, n):\n'
                '            return x, int(n)\n'
                '        self._s = jax.jit(step)\n'),
        })
        fs = run(root)
        assert codes(fs) == {"R301"}


# --------------------------------------------------------------- fault-seam


class TestFaultSeam:
    def test_armed_without_guard_flags(self, tmp_path):
        root = write_tree(tmp_path, {
            "tests/test_chaos.py": (
                'CFG = {"faults": {"host.pipe_wrote": "crash@nth=2"}}\n'),
            "symmetry_tpu/utils/faults.py": (
                'class FaultInjector:\n'
                '    pass\n'),
        })
        fs = run(root)
        assert codes(fs) == {"S401"}
        assert fs[0].symbol == "host.pipe_wrote"

    def test_guard_without_arming_flags(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/host.py": (
                'from symmetry_tpu.utils.faults import FAULTS\n'
                'def write(frame):\n'
                '    if FAULTS.enabled and'
                ' FAULTS.point("host.pipe_write"):\n'
                '        return\n'),
        })
        fs = run(root)
        assert codes(fs) == {"S402"}
        assert fs[0].symbol == "host.pipe_write"

    def test_matched_pair_and_env_string_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/host.py": (
                'from symmetry_tpu.utils.faults import FAULTS\n'
                'def write(frame):\n'
                '    if FAULTS.enabled and'
                ' FAULTS.point("host.pipe_write"):\n'
                '        return\n'),
            "tests/test_chaos.py": (
                'SPEC = "host.pipe_write=crash@nth=2"\n'),
        })
        assert run(root) == []

    def test_self_contained_injector_test_exempt(self, tmp_path):
        root = write_tree(tmp_path, {
            "tests/test_faults.py": (
                'from symmetry_tpu.utils.faults import FAULTS\n'
                'def test_roundtrip():\n'
                '    FAULTS.load({"x.y": "error"})\n'
                '    assert FAULTS.point("x.y") is False\n'),
        })
        assert run(root) == []


# -------------------------------------------------------- metric-names


METRICS_PY = '''\
class MetricName:
    REQS = "sym_t_requests_total"
    TOKS = "sym_t_tokens_total"
    DEAD = "sym_t_never_emitted_total"
'''


class TestMetricNames:
    def test_raw_literal_unregistered_and_dead_flag(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/utils/metrics.py": METRICS_PY,
            "symmetry_tpu/provider/provider.py": (
                'from symmetry_tpu.utils.metrics import METRICS, MetricName\n'
                'def init():\n'
                # registered name spelled raw (M101)…
                '    METRICS.counter("sym_t_requests_total")\n'
                # …a name the registry never heard of (M102)…
                '    METRICS.gauge("sym_t_typo_total")\n'
                # …a nonexistent registry attribute (M102)…
                '    METRICS.histogram(MetricName.TYPO)\n'
                # …and one clean emission
                '    METRICS.counter(MetricName.TOKS)\n'),
        })
        fs = [f for f in run(root) if f.checker == "metric-names"]
        got = codes(fs)
        assert got == {"M101", "M102", "M103"}
        assert {f.symbol for f in fs if f.code == "M101"} == \
            {"sym_t_requests_total"}
        assert {f.symbol for f in fs if f.code == "M102"} == \
            {"sym_t_typo_total", "MetricName.TYPO"}
        # DEAD registered but never emitted; REQS only emitted raw —
        # raw emission still counts as emitted, so it is not M103.
        assert {f.symbol for f in fs if f.code == "M103"} == \
            {"sym_t_never_emitted_total"}
        # M103 anchors at the registry assignment, not an emitter
        (dead,) = [f for f in fs if f.code == "M103"]
        assert dead.path == "symmetry_tpu/utils/metrics.py"

    def test_constant_emissions_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/utils/metrics.py": (
                'class MetricName:\n'
                '    REQS = "sym_t_requests_total"\n'
                '    LAT = "sym_t_lat_seconds"\n'),
            "symmetry_tpu/engine/scheduler.py": (
                'from symmetry_tpu.utils.metrics import METRICS, MetricName\n'
                'def init(self):\n'
                '    self._m = METRICS.counter(MetricName.REQS, "reqs")\n'
                '    METRICS.histogram(MetricName.LAT, labels=("kind",))\n'),
        })
        assert [f for f in run(root) if f.checker == "metric-names"] == []

    def test_tests_and_other_receivers_out_of_scope(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/utils/metrics.py": (
                'class MetricName:\n'
                '    REQS = "sym_t_requests_total"\n'),
            # tests pin names as raw literals deliberately — not scanned
            "tests/test_metrics.py": (
                'def test_x(METRICS):\n'
                '    METRICS.counter("sym_t_whatever_total")\n'),
            # a Tracer's .counter/.histogram is NOT a registry emission
            "symmetry_tpu/engine/scheduler.py": (
                'from symmetry_tpu.utils.metrics import METRICS, MetricName\n'
                'def f(self):\n'
                '    self.tracer.counter("occupancy", 1)\n'
                '    self.tracer.histogram("x_s")\n'
                '    METRICS.counter(MetricName.REQS)\n'),
        })
        assert [f for f in run(root) if f.checker == "metric-names"] == []

    def test_real_registry_fully_emitted(self):
        # The real repo: every MetricName constant must have a live
        # emission site and no emitter may bypass the registry — the
        # CI-gate contract, pinned here independently of the baseline.
        fs = [f for f in run(REPO) if f.checker == "metric-names"]
        assert fs == [], [f.render() for f in fs]


# ----------------------------------------------------- baseline + runner


class TestBaselineAndRunner:
    MISMATCH = {
        "symmetry_tpu/protocol/keys.py": KEYS_PY,
        "symmetry_tpu/engine/host.py": (
            'from symmetry_tpu.protocol.keys import HostOp\n'
            'def emit(w):\n'
            '    w({"op": HostOp.SUBMIT})\n'),
    }

    def test_baseline_suppresses_by_fingerprint(self, tmp_path):
        root = write_tree(tmp_path, self.MISMATCH)
        fs = run(root)
        assert fs and all(not f.baselined for f in fs)
        bl = Baseline([{"fingerprint": f.fingerprint, "reason": "test"}
                       for f in fs])
        fs2 = run(root, baseline=bl)
        assert fs2 and all(f.baselined for f in fs2)
        assert bl.unused() == []

    def test_baseline_requires_reasons(self, tmp_path):
        path = tmp_path / "bl.json"
        path.write_text(json.dumps(
            {"suppressions": [{"fingerprint": "X:y:z"}]}))
        with pytest.raises(ValueError, match="no\\s+reason"):
            Baseline.load(str(path))

    def _symlint(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "symlint.py"),
             *args],
            capture_output=True, text=True)

    def test_runner_exits_nonzero_on_seeded_wire_mismatch(self, tmp_path):
        # The CI-gate contract: a produced-but-never-consumed op must
        # fail the step.
        root = write_tree(tmp_path, self.MISMATCH)
        r = self._symlint("--root", root)
        assert r.returncode == 1
        assert "W102" in r.stdout and "submit" in r.stdout

    def test_runner_json_schema(self, tmp_path):
        root = write_tree(tmp_path, self.MISMATCH)
        r = self._symlint("--root", root, "--json")
        assert r.returncode == 1
        report = json.loads(r.stdout)
        assert report["version"] == 1
        assert set(report["counts"]) == {"total", "new", "baselined"}
        assert report["counts"]["new"] == len(report["findings"]) > 0
        f = report["findings"][0]
        assert set(f) == {"checker", "code", "path", "line", "message",
                          "symbol", "fingerprint", "baselined"}
        assert f["fingerprint"].startswith(f["code"] + ":")
        assert [s.name for s in ALL_CHECKERS] == report["checkers"]

    def test_runner_checker_filter_and_clean_exit(self, tmp_path):
        root = write_tree(tmp_path, self.MISMATCH)
        # the mismatch is wire-only: filtering to fault-seam is clean
        r = self._symlint("--root", root, "--checker", "fault-seam")
        assert r.returncode == 0, r.stdout + r.stderr

    def test_path_filter_keeps_cross_file_context(self, tmp_path):
        # Positional paths filter the REPORT, not the scan: the clean
        # consumer file exits 0 even though the mismatch lives in the
        # producer file — and naming the producer still fails.
        root = write_tree(tmp_path, {
            **self.MISMATCH,
            "symmetry_tpu/provider/backends/tpu_native.py": (
                'from symmetry_tpu.protocol.keys import HostOp\n'
                'def pump(msg):\n'
                '    op = msg.get("op")\n'
                '    if op == HostOp.EVENT:\n'
                '        return msg\n'),
        })
        r = self._symlint("--root", root,
                          "symmetry_tpu/protocol/keys.py")
        assert r.returncode == 0, r.stdout + r.stderr
        r = self._symlint("--root", root, "symmetry_tpu/engine/host.py")
        assert r.returncode == 1 and "W102" in r.stdout
        # a typo'd filter path is a broken invocation, not a clean run
        r = self._symlint("--root", root, "no/such/file.py")
        assert r.returncode == 2 and "matched no scanned file" in r.stderr

    def test_unused_baseline_entry_reported_and_strict(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/empty.py": "X = 1\n"})
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"suppressions": [
            {"fingerprint": "W102:gone.py:ghost", "reason": "stale"}]}))
        r = self._symlint("--root", root, "--baseline", str(bl))
        assert r.returncode == 0 and "matched nothing" in r.stderr
        r = self._symlint("--root", root, "--baseline", str(bl),
                          "--strict-baseline")
        assert r.returncode == 1

    def test_checker_filter_does_not_stale_other_checkers_entries(
            self, tmp_path):
        # A C202 suppression is not stale just because this run was
        # wire-contract-only — pruning on that advice would break the
        # next full run.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine/empty.py": "X = 1\n"})
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"suppressions": [
            {"fingerprint": "C202:a.py:Cls.attr", "reason": "owned"}]}))
        r = self._symlint("--root", root, "--baseline", str(bl),
                          "--checker", "wire-contract",
                          "--strict-baseline")
        assert r.returncode == 0 and "matched nothing" not in r.stderr
        # …but the same entry IS stale when its checker runs
        r = self._symlint("--root", root, "--baseline", str(bl),
                          "--checker", "concurrency", "--strict-baseline")
        assert r.returncode == 1 and "matched nothing" in r.stderr


# ------------------------------------------------------------- self-test


class TestRepoClean:
    def test_repo_runs_clean_modulo_baseline(self):
        """The acceptance gate, from the inside: zero non-baselined
        findings on this checkout, and no stale baseline entries."""
        bl = Baseline.load(os.path.join(REPO, "tools",
                                        "symlint_baseline.json"))
        findings = run(REPO, baseline=bl)
        fresh = [f for f in findings if not f.baselined]
        assert fresh == [], "\n".join(f.render() for f in fresh)
        assert bl.unused() == [], (
            "stale baseline entries — prune tools/symlint_baseline.json")

    def test_host_op_registry_matches_protocol_docstring_ops(self):
        # The registry the wire checker pivots on must cover the ops the
        # engine host actually dispatches (drift here would quietly
        # weaken every W-code).
        from symmetry_tpu.protocol.keys import HOST_OPS
        for op in ("submit", "adopt", "cancel", "clock", "trace",
                   "stats", "shutdown", "ready", "event", "events",
                   "handoff"):
            assert op in HOST_OPS


class TestHostStatsLockRegression:
    """Regression for the two real C202 findings symlint surfaced:
    EngineHost.handoff_stats / adopt_stats were mutated from the
    pipe-reader thread AND the engine thread without a lock. The fix
    takes _wlock around every mutation; this hammers the handoff path
    from two threads and requires exact counts."""

    def test_emit_handoff_counters_are_exact_under_contention(self):
        from symmetry_tpu.engine.host import EngineHost

        host = EngineHost(None)

        class _Eng:
            kv_quant = False
            prefix_block = 8

        host._engine = _Eng()
        host._write = lambda obj, events=0: None  # no real pipe
        n, threads = 200, 4
        old = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        try:
            def hammer():
                for i in range(n):
                    host._emit_handoff(f"r{i}", [1, 2, 3], 0, None)

            ts = [threading.Thread(target=hammer) for _ in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            sys.setswitchinterval(old)
        assert host.handoff_stats["frames"] == n * threads
        assert host.handoff_stats["routing_only"] == n * threads


# ------------------------------------------------- dataflow engine (CFG)


class _Probe:
    """Trivial semantics: the state is the frozenset of statement lines
    a path executed; at_exit records (exceptional, lines). Pins the
    CFG's edge structure without any checker logic in the way."""

    def __init__(self, prune=None):
        self.exits = []
        self.prune = prune  # (test_line, taken) branches to cut

    def initial(self):
        return frozenset()

    def transfer(self, node, state):
        line = getattr(node.stmt, "lineno", None)
        post = state | {line} if line is not None else state
        return post, post, ()

    def on_branch(self, test, state, taken):
        if self.prune and (getattr(test, "lineno", None), taken) \
                in self.prune:
            return None
        return state

    def at_exit(self, state, exceptional):
        self.exits.append((exceptional, state))
        return ()


def _analyze_probe(src, **kw):
    import ast as _ast

    from symmetry_tpu.analysis.dataflow import analyze

    func = _ast.parse(src).body[0]
    probe = _Probe(**kw)
    analyze(func, probe)
    return probe


class TestDataflowEngine:
    def test_raising_call_reaches_both_exits(self):
        p = _analyze_probe("def f():\n"
                           "    boom()\n")
        kinds = {e for e, _ in p.exits}
        assert kinds == {False, True}

    def test_finally_runs_on_normal_and_exception_paths(self):
        p = _analyze_probe("def f():\n"
                           "    try:\n"
                           "        boom()\n"        # line 3
                           "    finally:\n"
                           "        note = 1\n")     # line 5
        # EVERY exit — the fallthrough and the unwind — saw the
        # finally body (cloned per continuation, not joined).
        assert p.exits and all(5 in lines for _, lines in p.exits)
        assert {e for e, _ in p.exits} == {False, True}

    def test_except_handler_catches_and_continues(self):
        p = _analyze_probe("def f():\n"
                           "    try:\n"
                           "        boom()\n"
                           "    except Exception:\n"
                           "        cleanup = 1\n"   # line 5
                           "    tail = 1\n")         # line 6
        # catch-all: no exceptional exit escapes the function
        assert {e for e, _ in p.exits} == {False}
        # some path took handler → tail
        assert any({5, 6} <= lines for _, lines in p.exits)

    def test_narrow_handler_propagates_past(self):
        p = _analyze_probe("def f():\n"
                           "    try:\n"
                           "        boom()\n"
                           "    except KeyError:\n"
                           "        pass\n")
        # the exception may match no handler and keep unwinding
        assert {e for e, _ in p.exits} == {False, True}

    def test_early_return_skips_tail(self):
        p = _analyze_probe("def f(a):\n"
                           "    if a:\n"
                           "        return 1\n"      # line 3
                           "    tail = 1\n")         # line 4
        normal = [lines for e, lines in p.exits if not e]
        assert any(3 in lines and 4 not in lines for lines in normal)
        assert any(4 in lines and 3 not in lines for lines in normal)

    def test_branch_pruning_cuts_paths(self):
        p = _analyze_probe("def f(a):\n"
                           "    if a:\n"             # test line 2
                           "        dead = 1\n"      # line 3
                           "    tail = 1\n",
                           prune={(2, True)})
        assert p.exits
        assert all(3 not in lines for _, lines in p.exits)

    def test_with_and_while_edges(self):
        p = _analyze_probe("def f(ctx, flag):\n"
                           "    with ctx():\n"
                           "        boom()\n"
                           "    while flag:\n"
                           "        flag = step()\n"
                           "    done = 1\n")         # line 6
        kinds = {e for e, _ in p.exits}
        assert kinds == {False, True}   # body raise escapes the with
        assert any(6 in lines for e, lines in p.exits if not e)

    def test_store_subscript_is_not_an_exception_edge(self):
        # `d[k] = v` cannot realistically raise — fabricating an
        # unwind edge out of every container store would drown the
        # lifecycle checker in phantom leak paths (the scheduler's
        # hit_units shape).
        p = _analyze_probe("def f(d, k, v):\n"
                           "    d[k] = v\n")
        assert {e for e, _ in p.exits} == {False}
        p = _analyze_probe("def f(d, k):\n"
                           "    v = d[k]\n")         # a Load CAN raise
        assert {e for e, _ in p.exits} == {False, True}


# ----------------------------------------------------- lifecycle (L4xx)


def lifecycle_codes(root) -> set[str]:
    return {f.code for f in run(root) if f.checker == "lifecycle"}


class TestLifecycle:
    def test_exception_path_leak_flags_L402(self, tmp_path):
        # The PR-12 shape: device work between plan_insert and the
        # commit/abort pair, outside any try — the unwind leaks the pin.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": (
                "def store(idx, tokens, dev):\n"
                "    plan = idx.plan_insert(tokens)\n"
                "    if plan is None:\n"
                "        return\n"
                "    dev.scatter(plan.new_ids)\n"
                "    plan.commit()\n"),
        })
        assert "L402" in lifecycle_codes(root)

    def test_abort_on_exception_path_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": (
                "def store(idx, tokens, dev):\n"
                "    plan = idx.plan_insert(tokens)\n"
                "    if plan is None:\n"
                "        return\n"
                "    try:\n"
                "        dev.scatter(plan.new_ids)\n"
                "    except Exception:\n"
                "        plan.abort()\n"
                "        raise\n"
                "    plan.commit()\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_release_in_finally_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def place(idx, t, eng):\n"
                "    hit = idx.lookup(t)\n"
                "    if hit is None:\n"
                "        return 0\n"
                "    try:\n"
                "        return eng.seed(hit.length)\n"
                "    finally:\n"
                "        hit.release()\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_normal_path_leak_flags_L401(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def peek(idx, t):\n"
                "    hit = idx.lookup(t)\n"
                "    if hit is not None:\n"
                "        log(hit.length)\n"
                "    return 1\n"),
        })
        assert "L401" in lifecycle_codes(root)

    def test_resume_journal_exception_leak_flags_L402(self, tmp_path):
        # The resume-journal protocol (PR 14): track() on admission must
        # release() on the exception edge too — a leaked entry is a
        # finished request the death paths would stamp forever.
        root = write_tree(tmp_path, {
            "symmetry_tpu/backend.py": (
                "def serve(self, request_id, host):\n"
                "    entry = self._journal.track(request_id)\n"
                "    host.submit(request_id)\n"
                "    entry.release()\n"),
        })
        assert "L402" in lifecycle_codes(root)

    def test_resume_journal_release_in_finally_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/backend.py": (
                "def serve(self, request_id, host):\n"
                "    entry = self._journal.track(request_id)\n"
                "    try:\n"
                "        host.submit(request_id)\n"
                "        entry.note(3)\n"
                "    finally:\n"
                "        entry.release()\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_resume_journal_hint_scopes_track(self, tmp_path):
        # `track` on a non-journal receiver is someone else's method —
        # the receiver hint keeps the spec from claiming it.
        root = write_tree(tmp_path, {
            "symmetry_tpu/other.py": (
                "def follow(self, request_id):\n"
                "    t = self._watcher.track(request_id)\n"
                "    return t\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_ledger_entry_leak_flags_L401(self, tmp_path):
        # The symledger protocol (PR 20): a tracked cost account that
        # no path finishes or releases never folds its device seconds —
        # conservation silently stops closing.
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def run(self, req):\n"
                "    entry = self.ledger.track(req.id)\n"
                "    if entry is not None:\n"
                "        entry.book_device('decode', 0.1)\n"
                "    return 1\n"),
        })
        assert "L401" in lifecycle_codes(root)

    def test_ledger_entry_finish_or_release_clean(self, tmp_path):
        # Either closer resolves the entry (both idempotent), and the
        # optional acquire means the None miss path needs no close.
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def run(self, req, handoff):\n"
                "    entry = self.ledger.track(req.id)\n"
                "    if entry is None:\n"
                "        return 0\n"
                "    try:\n"
                "        entry.book_device('decode', 0.1)\n"
                "    finally:\n"
                "        if handoff:\n"
                "            entry.release('handoff')\n"
                "        else:\n"
                "            entry.finish('stop')\n"
                "    return 1\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_double_commit_flags_L403(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": (
                "def twice(idx, tokens):\n"
                "    plan = idx.plan_insert(tokens)\n"
                "    if plan is None:\n"
                "        return\n"
                "    plan.commit()\n"
                "    plan.commit()\n"),
        })
        assert "L403" in lifecycle_codes(root)

    def test_read_after_abort_flags_L404(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": (
                "def freed(idx, tokens):\n"
                "    plan = idx.plan_insert(tokens)\n"
                "    if plan is None:\n"
                "        return None\n"
                "    plan.abort()\n"
                "    return plan.new_ids\n"),
        })
        assert "L404" in lifecycle_codes(root)

    def test_none_check_before_release_is_not_a_use(self, tmp_path):
        # The scheduler's cleanup-handler idiom: a bare `hit is not
        # None` after a release on some path reads the NAME, not the
        # resource.
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def place(idx, t, eng):\n"
                "    hit = idx.lookup(t)\n"
                "    try:\n"
                "        if hit is not None:\n"
                "            eng.seed(hit.length)\n"
                "            hit.release()\n"
                "            hit = None\n"
                "    except Exception:\n"
                "        if hit is not None:\n"
                "            hit.release()\n"
                "        raise\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_returning_an_attribute_is_not_a_transfer(self, tmp_path):
        # `return hit.length` READS the pin, it does not hand it off —
        # the leak must still be reported (regression: the escape walk
        # once matched the bare name inside the attribute chain).
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def depth(idx, t):\n"
                "    hit = idx.lookup(t)\n"
                "    if hit is None:\n"
                "        return 0\n"
                "    return hit.length\n"),
        })
        assert "L401" in lifecycle_codes(root)

    def test_conditional_release_in_finally_clean(self, tmp_path):
        # The standard guarded-cleanup idiom: narrowing must survive
        # inside the finally clone's exception continuation
        # (regression: the clone's branch edges were relabeled
        # exceptional, bypassing on_branch).
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def place(idx, t, eng):\n"
                "    hit = idx.lookup(t)\n"
                "    try:\n"
                "        eng.seed(t)\n"
                "    finally:\n"
                "        if hit is not None:\n"
                "            hit.release()\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_ownership_transfer_ends_tracking(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                # returned, stored on self, packed into a container
                # slot (the scheduler's hit_units tuple shape), and
                # passed onward to a callee that now owns it
                "def a(idx, t):\n"
                "    hit = idx.lookup(t)\n"
                "    return hit\n"
                "def b(self, idx, t):\n"
                "    self.hit = idx.lookup(t)\n"
                "def c(idx, t, units):\n"
                "    hit = idx.lookup(t)\n"
                "    if hit is None:\n"
                "        return\n"
                "    units[0] = (hit, [t])\n"
                "def d(idx, t, eng):\n"
                "    hit = idx.lookup(t)\n"
                "    if hit is None:\n"
                "        return\n"
                "    eng.start_chunked(t, hit=hit)\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_bare_lock_acquire_flags_and_with_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/host.py": (
                "def bad(self):\n"
                "    self._lock.acquire()\n"
                "    self.n = work()\n"
                "    return self.n\n"),
            "symmetry_tpu/host2.py": (
                "def good(self):\n"
                "    self._lock.acquire()\n"
                "    try:\n"
                "        self.n = work()\n"
                "    finally:\n"
                "        self._lock.release()\n"
                "    return self.n\n"),
        })
        fs = [f for f in run(root) if f.checker == "lifecycle"]
        assert {f.path for f in fs} == {"symmetry_tpu/host.py"}

    def test_discarded_acquire_flags(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def warm(idx, t):\n"
                "    idx.lookup(t)\n"),   # pin dropped on the floor
        })
        assert "L401" in lifecycle_codes(root)

    def test_tests_and_tools_out_of_scope(self, tmp_path):
        root = write_tree(tmp_path, {
            "tests/test_x.py": (
                "def test_pin(idx):\n"
                "    hit = idx.lookup([1])\n"
                "    assert hit.length\n"),
            "tools/probe.py": (
                "def main(idx):\n"
                "    plan = idx.plan_insert([1])\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_tuple_pack_into_local_then_return_transfers(self, tmp_path):
        # `pair = (hit, t); return pair` hands the pin to the caller
        # just as surely as `return hit` — packing through a plain
        # local alias must not read as a leak.
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def place(idx, t):\n"
                "    hit = idx.lookup(t)\n"
                "    pair = (hit, t)\n"
                "    return pair\n"),
        })
        assert lifecycle_codes(root) == set()

    def test_attribute_read_through_local_still_leaks(self, tmp_path):
        # The transfer above is maximal-reference only: copying an
        # ATTRIBUTE of the handle into a local reads the pin without
        # moving it — dropping the handle afterwards is still L401.
        root = write_tree(tmp_path, {
            "symmetry_tpu/sched.py": (
                "def peek(idx, t):\n"
                "    hit = idx.lookup(t)\n"
                "    n = hit.length\n"
                "    return n\n"),
        })
        assert "L401" in lifecycle_codes(root)


# ------------------------------------------------------ donation (D5xx)


def donation_codes(root) -> set[str]:
    return {f.code for f in run(root) if f.checker == "donation"}


_DON_PRELUDE = (
    "import jax\n"
    "class E:\n"
    "    def build(self, step):\n"
    "        self._decode = jax.jit(step, donate_argnums=(1,))\n"
)


class TestDonation:
    def test_read_after_donation_flags_D501(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": _DON_PRELUDE + (
                "    def loop(self, tok):\n"
                "        out = self._decode(tok, self.state)\n"
                "        return probe(self.state)\n"),
        })
        assert "D501" in donation_codes(root)

    def test_rebind_idiom_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": _DON_PRELUDE + (
                "    def loop(self, tok):\n"
                "        self.state = self._decode(tok, self.state)\n"
                "        return probe(self.state)\n"),
        })
        assert donation_codes(root) == set()

    def test_partial_path_rebind_still_flags(self, tmp_path):
        # The bug is path-shaped: the happy arm rebinds, the other arm
        # reads the stale name.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": _DON_PRELUDE + (
                "    def loop(self, tok, ok):\n"
                "        out = self._decode(tok, self.state)\n"
                "        if ok:\n"
                "            self.state = out\n"
                "        return probe(self.state)\n"),
        })
        assert "D501" in donation_codes(root)

    def test_discarded_result_flags_D502(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": _DON_PRELUDE + (
                "    def loop(self, tok):\n"
                "        self._decode(tok, self.state)\n"),
        })
        assert "D502" in donation_codes(root)

    def test_decorator_registration_and_flag(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/ops.py": (
                "import functools, jax\n"
                "@functools.partial(jax.jit, donate_argnums=(0,))\n"
                "def step(cache, tok):\n"
                "    return cache\n"
                "def drive(cache, tok):\n"
                "    new = step(cache, tok)\n"
                "    return probe(cache)\n"),
        })
        assert "D501" in donation_codes(root)

    def test_augassign_read_of_donated_path_flags_D501(self, tmp_path):
        # `self.state += d` reads the donated buffer to compute the new
        # value — an implicit Load the Store-ctx target hides, and the
        # rebind half must not launder it.
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": _DON_PRELUDE + (
                "    def loop(self, tok, d):\n"
                "        out = self._decode(tok, self.state)\n"
                "        self.state += d\n"
                "        return out\n"),
        })
        assert "D501" in donation_codes(root)

    def test_augassign_after_rebind_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": _DON_PRELUDE + (
                "    def loop(self, tok, d):\n"
                "        self.state = self._decode(tok, self.state)\n"
                "        self.state += d\n"
                "        return self.state\n"),
        })
        assert donation_codes(root) == set()

    def test_deferred_lambda_body_is_not_a_read(self, tmp_path):
        # The lambda runs later — after the very next statement has
        # rebound the name — so its body must not count as a read at
        # the definition site (nested defs likewise).
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": _DON_PRELUDE + (
                "    def loop(self, tok, sched):\n"
                "        out = self._decode(tok, self.state)\n"
                "        sched(lambda: probe(self.state))\n"
                "        self.state = out\n"
                "        return self.state\n"),
        })
        assert donation_codes(root) == set()

    def test_non_donating_jit_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/ops.py": (
                "import jax\n"
                "class E:\n"
                "    def build(self, fn):\n"
                "        self._f = jax.jit(fn, static_argnums=(2,))\n"
                "    def loop(self, tok):\n"
                "        out = self._f(tok, self.state, 1)\n"
                "        return probe(self.state)\n"),
        })
        assert donation_codes(root) == set()


# --------------------------------------------------------- knobs (K6xx)


_KNOB_CONFIG = (
    "from dataclasses import dataclass\n"
    "@dataclass\n"
    "class TpuConfig:\n"
    "    decode_block: int = 16\n"
    "    max_queue: int = 0\n"
    "    dead_knob: int = 1\n"
)


class TestKnobs:
    def test_all_three_drifts_flag(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/provider/config.py": _KNOB_CONFIG,
            "symmetry_tpu/engine.py": (
                "def build(tpu_cfg):\n"
                "    q = getattr(tpu_cfg, 'max_queue', 0)\n"
                "    return tpu_cfg.decode_block + q\n"),
            # decode_block documented; a ghost knob documented; a
            # module path that must NOT parse as a knob mention
            "README.md": (
                "Set `tpu.decode_block` to tune dispatch width.\n"
                "Set `tpu.ghost_knob` for good luck.\n"
                "Run `python -m symmetry_tpu.engine.host` by hand.\n"),
        })
        fs = [f for f in run(root) if f.checker == "knobs"]
        by_code = {f.code: f for f in fs}
        assert set(by_code) == {"K601", "K602", "K603"}
        assert by_code["K601"].symbol == "tpu.max_queue"    # read, undoc
        assert by_code["K602"].symbol == "tpu.ghost_knob"   # doc, unknown
        assert by_code["K602"].path == "README.md"
        assert by_code["K603"].symbol == "tpu.dead_knob"    # never read

    def test_aligned_docs_and_reads_clean(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/provider/config.py": _KNOB_CONFIG,
            "symmetry_tpu/engine.py": (
                "def build(cfg):\n"
                "    tpu_cfg = cfg.tpu\n"
                "    k = tpu_cfg.dead_knob\n"
                "    return tpu_cfg.decode_block + tpu_cfg.max_queue + k\n"),
            "README.md": ("`tpu.decode_block`, `tpu.max_queue` and\n"
                          "`tpu.dead_knob` are documented here.\n"),
        })
        assert [f for f in run(root) if f.checker == "knobs"] == []

    def test_non_tpu_receiver_is_not_a_read(self, tmp_path):
        # `job.decode_block` on some unrelated object must not count as
        # a knob read (the receiver-hint is what scopes the scan).
        root = write_tree(tmp_path, {
            "symmetry_tpu/provider/config.py": _KNOB_CONFIG,
            "symmetry_tpu/other.py": (
                "def f(job):\n"
                "    return job.decode_block\n"),
            "README.md": ("`tpu.decode_block`, `tpu.max_queue`,\n"
                          "`tpu.dead_knob`.\n"),
        })
        fs = [f for f in run(root) if f.checker == "knobs"]
        assert {f.code for f in fs} == {"K603"}
        assert {f.symbol for f in fs} == {
            "tpu.decode_block", "tpu.max_queue", "tpu.dead_knob"}

    def test_no_registry_no_findings(self, tmp_path):
        root = write_tree(tmp_path, {
            "symmetry_tpu/engine.py": "def f(tpu_cfg):\n    return 1\n",
        })
        assert [f for f in run(root) if f.checker == "knobs"] == []


# ------------------------------------------------------- SARIF (--sarif)


class TestSarif:
    SEEDED = {
        "symmetry_tpu/protocol/keys.py": KEYS_PY,
        "symmetry_tpu/engine/host.py": (
            'from symmetry_tpu.protocol.keys import HostOp\n'
            'def emit(w):\n'
            '    w({"op": HostOp.SUBMIT})\n'
            '    w({"op": HostOp.EVENT})\n'),
    }

    def _run_sarif(self, tmp_path, *extra):
        root = write_tree(tmp_path, self.SEEDED)
        out = os.path.join(str(tmp_path), "out.sarif")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "symlint.py"),
             "--root", root, "--checker", "wire-contract",
             "--sarif", out, *extra],
            capture_output=True, text=True)
        with open(out, encoding="utf-8") as fh:
            return r, json.load(fh)

    def test_matches_golden(self, tmp_path):
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"version": 1, "suppressions": [
            {"fingerprint":
                 "W102:symmetry_tpu/engine/host.py:submit",
             "reason": "seeded suppression for the golden file"}]}))
        r, doc = self._run_sarif(tmp_path, "--baseline", str(bl))
        assert r.returncode == 1   # the EVENT finding is new
        with open(os.path.join(REPO, "tests", "data",
                               "sarif_golden.json"),
                  encoding="utf-8") as fh:
            golden = json.load(fh)
        assert doc == golden

    def test_schema_shape(self, tmp_path):
        r, doc = self._run_sarif(tmp_path)
        assert doc["version"] == "2.1.0"
        assert doc["$schema"].endswith("sarif-2.1.0.json")
        run_ = doc["runs"][0]
        driver = run_["tool"]["driver"]
        assert driver["name"] == "symlint"
        rule_ids = {rule["id"] for rule in driver["rules"]}
        assert {"W101", "W102", "W107"} <= rule_ids
        for res in run_["results"]:
            assert res["ruleId"] in rule_ids
            assert res["level"] in ("error", "note")
            loc = res["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"].endswith(".py")
            assert loc["region"]["startLine"] >= 1
            assert res["partialFingerprints"]["symlintFingerprint/v1"]
        # no baseline → nothing suppressed, everything an error
        assert all(res["level"] == "error" and "suppressions" not in res
                   for res in run_["results"])


# --------------------------------------- randomized CFG ground-truth test


class _Gen:
    """Random function generator with an independent reference model.

    Emits nested if/try-finally/try-except/early-return bodies over one
    `idx.lookup` handle, built from a grammar small enough to simulate
    EXACTLY: `outcomes(body)` enumerates every (exit-kind, still-held)
    pair the dataflow engine should discover — including the engine's
    own conventions (any call can raise; a release that raises still
    released; catch-all handlers stop the unwind). The lifecycle
    checker's leak verdict must equal the reference's on every seed; a
    divergence is a CFG or transfer bug, pinpointed by the seed.
    """

    def __init__(self, rng):
        self.rng = rng

    def body(self, depth):
        n = self.rng.randint(1, 3)
        return [self.item(depth) for _ in range(n)]

    def item(self, depth):
        atoms = ["noop", "boom", "release", "relif", "ret"]
        if depth <= 0:
            return self.rng.choice(atoms)
        kind = self.rng.choice(atoms + ["if", "tryfin", "tryexc"])
        if kind == "if":
            return ("if", self.body(depth - 1), self.body(depth - 1))
        if kind == "tryfin":
            fin = [self.rng.choice(["noop", "release", "relif"])]
            return ("tryfin", self.body(depth - 1), fin)
        if kind == "tryexc":
            return ("tryexc", self.body(depth - 1), self.body(depth - 1))
        return kind

    # ------------------------------------------------------------ render

    def render(self, items, ind):
        pad = "    " * ind
        out = []
        for it in items:
            if it == "noop":
                out.append(f"{pad}x = 1")
            elif it == "boom":
                out.append(f"{pad}boom()")
            elif it == "release":
                out.append(f"{pad}h.release()")
            elif it == "relif":
                # the guarded-cleanup idiom: past the prelude h is
                # never None, so this is exactly a release — but the
                # CHECKER must prove that via branch narrowing (held
                # handles are not None), incl. inside finally clones
                out.append(f"{pad}if h is not None:")
                out.append(f"{pad}    h.release()")
            elif it == "ret":
                out.append(f"{pad}return 1")
            elif it[0] == "if":
                out.append(f"{pad}if flag:")
                out += self.render(it[1], ind + 1)
                out.append(f"{pad}else:")
                out += self.render(it[2], ind + 1)
            elif it[0] == "tryfin":
                out.append(f"{pad}try:")
                out += self.render(it[1], ind + 1)
                out.append(f"{pad}finally:")
                out += self.render(it[2], ind + 1)
            elif it[0] == "tryexc":
                out.append(f"{pad}try:")
                out += self.render(it[1], ind + 1)
                out.append(f"{pad}except Exception:")
                out += self.render(it[2], ind + 1)
        return out

    # --------------------------------------------------------- reference

    def outcomes(self, items, held):
        """Exact exit set of `items` entered holding `held`:
        {(kind, held')} with kind in fall/ret/exc."""
        outs = set()
        cur = {held}
        for it in items:
            nxt = set()
            for h in cur:
                for kind, h2 in self._one(it, h):
                    if kind == "fall":
                        nxt.add(h2)
                    else:
                        outs.add((kind, h2))
            cur = nxt
        return outs | {("fall", h) for h in cur}

    def _one(self, it, held):
        if it == "noop":
            return {("fall", held)}
        if it == "boom":
            return {("fall", held), ("exc", held)}
        if it in ("release", "relif"):
            # the engine's convention: a release that raises released;
            # relif's guard is always true past the prelude (and on an
            # already-released path the skip changes nothing)
            return {("fall", False), ("exc", False)}
        if it == "ret":
            return {("ret", held)}
        if it[0] == "if":
            return self.outcomes(it[1], held) | self.outcomes(it[2], held)
        if it[0] == "tryfin":
            outs = set()
            for kind, h in self.outcomes(it[1], held):
                for fk, fh in self.outcomes(it[2], h):
                    outs.add((kind if fk == "fall" else fk, fh))
            return outs
        if it[0] == "tryexc":
            outs = set()
            for kind, h in self.outcomes(it[1], held):
                if kind == "exc":
                    outs |= self.outcomes(it[2], h)
                else:
                    outs.add((kind, h))
            return outs
        raise AssertionError(it)


class TestRandomizedLifecycleGroundTruth:
    def test_checker_matches_reference_on_random_cfgs(self):
        import random

        from symmetry_tpu.analysis import lifecycle
        from symmetry_tpu.analysis.core import Project, parse_source

        verdicts = set()
        for seed in range(120):
            g = _Gen(random.Random(seed))
            items = g.body(depth=3)
            src = "\n".join(
                ["def f(idx, flag):",
                 "    h = idx.lookup([1])",
                 "    if h is None:",
                 "        return 0"]
                + g.render(items, 1)) + "\n"
            expect_leak = any(
                h for _, h in g.outcomes(items, held=True))
            sf = parse_source("symmetry_tpu/gen.py",
                              "symmetry_tpu/gen.py", src)
            assert sf.tree is not None, src
            fs = lifecycle.check(Project("", [sf]))
            got = {f.code for f in fs}
            assert got <= {"L401", "L402"}, (src, got)
            got_leak = bool(got)
            assert got_leak == expect_leak, (
                f"seed {seed}: checker={'leak' if got_leak else 'clean'} "
                f"reference={'leak' if expect_leak else 'clean'}\n{src}")
            verdicts.add(expect_leak)
        # the generator must exercise BOTH verdicts or this test is
        # vacuous
        assert verdicts == {True, False}
