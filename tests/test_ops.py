"""Unit tests for the tensor ops floor (rope/attention/sampling)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.ops import apply_rope, gqa_attention, rms_norm, sample_tokens


class TestRope:
    def test_position_zero_is_identity(self):
        x = jax.random.normal(jax.random.key(0), (2, 1, 4, 16))
        pos = jnp.zeros((2, 1), jnp.int32)
        np.testing.assert_allclose(apply_rope(x, pos), x, atol=1e-6)

    def test_preserves_norm(self):
        x = jax.random.normal(jax.random.key(1), (1, 8, 2, 32))
        pos = jnp.arange(8, dtype=jnp.int32)[None, :]
        out = apply_rope(x, pos)
        # Rotation acts on (i, i+d/2) pairs — pairwise norms are invariant.
        def pair_norms(a):
            h = a.shape[-1] // 2
            return a[..., :h] ** 2 + a[..., h:] ** 2
        np.testing.assert_allclose(pair_norms(out), pair_norms(x), atol=1e-4)

    def test_relative_property(self):
        # <rope(q,p), rope(k,p)> depends only on content for equal positions.
        q = jax.random.normal(jax.random.key(2), (1, 1, 1, 32))
        k = jax.random.normal(jax.random.key(3), (1, 1, 1, 32))
        def dot_at(p):
            pos = jnp.full((1, 1), p, jnp.int32)
            return jnp.sum(apply_rope(q, pos) * apply_rope(k, pos))
        np.testing.assert_allclose(dot_at(0), dot_at(17), rtol=1e-4)


class TestRmsNorm:
    def test_matches_reference_formula(self):
        x = np.random.default_rng(0).normal(size=(2, 3, 8)).astype(np.float32)
        w = np.random.default_rng(1).normal(size=(8,)).astype(np.float32)
        want = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-5) * w
        got = rms_norm(jnp.asarray(x), jnp.asarray(w))
        np.testing.assert_allclose(got, want, rtol=1e-5)


def naive_attention(q, k, v, q_pos, kv_len, window=None):
    """Straight numpy reference: per-sample, per-head loops."""
    B, S, nq, D = q.shape
    T, nkv = k.shape[1], k.shape[2]
    group = nq // nkv
    out = np.zeros_like(q)
    for b in range(B):
        for h in range(nq):
            kh = h // group
            for s in range(S):
                scores = q[b, s, h] @ k[b, :, kh].T / np.sqrt(D)
                mask = (np.arange(T) <= q_pos[b, s]) & (np.arange(T) < kv_len[b])
                if window is not None:
                    mask &= np.arange(T) > q_pos[b, s] - window
                scores = np.where(mask, scores, -1e30)
                p = np.exp(scores - scores.max())
                p /= p.sum()
                out[b, s, h] = p @ v[b, :, kh]
    return out


class TestAttention:
    @pytest.mark.parametrize("nq,nkv", [(4, 4), (4, 2), (8, 1)])
    def test_matches_naive(self, nq, nkv):
        rng = np.random.default_rng(42)
        B, S, T, D = 2, 3, 10, 8
        q = rng.normal(size=(B, S, nq, D)).astype(np.float32)
        k = rng.normal(size=(B, T, nkv, D)).astype(np.float32)
        v = rng.normal(size=(B, T, nkv, D)).astype(np.float32)
        q_pos = np.array([[4, 5, 6], [0, 1, 2]], np.int32)
        kv_len = np.array([7, 3], np.int32)
        got = gqa_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            jnp.asarray(q_pos), jnp.asarray(kv_len))
        want = naive_attention(q, k, v, q_pos, kv_len)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_sliding_window(self):
        rng = np.random.default_rng(7)
        B, S, T, D, nh = 1, 2, 12, 4, 2
        q = rng.normal(size=(B, S, nh, D)).astype(np.float32)
        k = rng.normal(size=(B, T, nh, D)).astype(np.float32)
        v = rng.normal(size=(B, T, nh, D)).astype(np.float32)
        q_pos = np.array([[8, 9]], np.int32)
        kv_len = np.array([10], np.int32)
        got = gqa_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            jnp.asarray(q_pos), jnp.asarray(kv_len),
                            sliding_window=4)
        want = naive_attention(q, k, v, q_pos, kv_len, window=4)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestSampling:
    def setup_method(self):
        self.logits = jnp.asarray(
            np.random.default_rng(0).normal(size=(4, 32)).astype(np.float32))

    def test_greedy_when_temperature_zero(self):
        out = sample_tokens(self.logits, jax.random.key(0),
                            temperature=jnp.zeros(4),
                            top_p=jnp.ones(4), top_k=jnp.zeros(4, jnp.int32))
        np.testing.assert_array_equal(out, jnp.argmax(self.logits, -1))

    def test_top_k_one_is_greedy(self):
        out = sample_tokens(self.logits, jax.random.key(1),
                            temperature=jnp.ones(4),
                            top_p=jnp.ones(4),
                            top_k=jnp.ones(4, jnp.int32))
        np.testing.assert_array_equal(out, jnp.argmax(self.logits, -1))

    def test_tiny_top_p_is_greedy(self):
        out = sample_tokens(self.logits, jax.random.key(2),
                            temperature=jnp.ones(4),
                            top_p=jnp.full(4, 1e-6),
                            top_k=jnp.zeros(4, jnp.int32))
        np.testing.assert_array_equal(out, jnp.argmax(self.logits, -1))

    def test_samples_follow_distribution(self):
        # Two-token vocab with known probabilities; check empirical frequency.
        logits = jnp.log(jnp.asarray([[0.8, 0.2]])).repeat(512, axis=0)
        out = sample_tokens(logits, jax.random.key(3),
                            temperature=jnp.ones(512),
                            top_p=jnp.ones(512), top_k=jnp.zeros(512, jnp.int32))
        frac = float(jnp.mean(out == 0))
        assert 0.7 < frac < 0.9

    def test_per_slot_controls_mixed(self):
        # Slot 0 greedy, slot 1 sampled — one call, both semantics.
        logits = jnp.asarray([[1.0, 5.0, 2.0], [1.0, 5.0, 2.0]])
        out = sample_tokens(logits, jax.random.key(4),
                            temperature=jnp.asarray([0.0, 1.0]),
                            top_p=jnp.ones(2), top_k=jnp.zeros(2, jnp.int32))
        assert int(out[0]) == 1
        assert 0 <= int(out[1]) < 3
