"""Failover: provider dies mid-stream → session requeue + client retry.

Round-2 verdict gap: the server marked dead providers offline but their
in-flight sessions just died and clients had no recovery. Now the server
expires a dead provider's sessions (registry.invalidate_sessions_for) and
SymmetryClient.chat_failover re-requests a provider with the dead one
excluded, completing the chat on the survivor (SURVEY §5.3).
"""

import asyncio
import time

import pytest

from symmetry_tpu.client.client import (
    ChatRestart,
    ChatResume,
    ClientError,
    DeadlineExceededError,
    ProviderBusyError,
    ProviderDiedMidStreamError,
    ProviderGoneError,
    ProviderRestartingError,
    SymmetryClient,
    busy_retry_backoff,
)
from symmetry_tpu.identity import Identity
from symmetry_tpu.provider.backends.base import (
    BackendRestartingError,
    InferenceBackend,
    StreamChunk,
)
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.server.broker import SymmetryServer
from symmetry_tpu.transport.memory import MemoryTransport


def run(coro):
    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(coro, 60))


class SlowBackend(InferenceBackend):
    """Streams one word per tick forever-ish — guarantees the kill lands
    mid-stream."""

    name = "slow"

    def __init__(self, config=None, delay=0.05, n=100) -> None:
        self._delay = delay
        self._n = n

    async def start(self) -> None: ...

    async def stop(self) -> None: ...

    async def healthy(self) -> bool:
        return True

    async def stream(self, request):
        for i in range(self._n):
            await asyncio.sleep(self._delay)
            yield StreamChunk(raw=f"data: {{\"choices\": [{{\"delta\": "
                                  f"{{\"content\": \"w{i} \"}}}}]}}",
                              text=f"w{i} ")


def provider_config(server_key_hex, name):
    return ConfigManager(config={
        "name": name, "public": True, "serverKey": server_key_hex,
        "modelName": "tiny:fo", "apiProvider": "echo",
        "dataCollectionEnabled": False,
    })


async def start_network(hub, server_ident, slow_first=True):
    server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
    await server.start("mem://server")
    p1 = SymmetryProvider(
        provider_config(server_ident.public_hex, "fo-p1"), transport=hub,
        identity=Identity.from_name("fo-p1"),
        backend=SlowBackend() if slow_first else None,
        server_address="mem://server")
    await p1.start("mem://fo-p1")
    await p1.wait_registered()
    p2 = SymmetryProvider(
        provider_config(server_ident.public_hex, "fo-p2"), transport=hub,
        identity=Identity.from_name("fo-p2"),
        server_address="mem://server")
    await p2.start("mem://fo-p2")
    await p2.wait_registered()
    return server, p1, p2


class TestFailover:
    def test_mid_stream_provider_death_completes_on_second(self):
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server")
            server, p1, p2 = await start_network(hub, ident)
            client = SymmetryClient(Identity.from_name("fo-cli"), hub)

            # The broker prefers the least-loaded provider; make p1 the
            # guaranteed first pick by marking p2 busier.
            server.registry.set_connections(
                p2.identity.public_hex, 5)

            events = []

            async def chat():
                # resume=False pins the LEGACY discard-and-restart mode
                # (the resume path has its own suite below): p1's
                # SlowBackend text is not a prefix of p2's echo, so a
                # splice would be wrong here by construction.
                async for item in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": "failover!"}],
                        resume=False):
                    events.append(item)

            async def killer():
                # wait until p1 is actually streaming, then hard-kill it
                while not p1._in_flight:
                    await asyncio.sleep(0.01)
                await asyncio.sleep(0.15)
                for peer in list(p1._client_peers):
                    await peer.close()
                await p1.stop(drain_timeout_s=0)

            await asyncio.gather(chat(), killer())

            restarts = [e for e in events if isinstance(e, ChatRestart)]
            assert len(restarts) == 1
            assert restarts[0].provider_key == p2.identity.public_hex
            # deltas after the restart come from p2's echo backend
            after = events[events.index(restarts[0]) + 1:]
            assert after and all(isinstance(d, str) for d in after)
            # p1's session is dead server-side
            assert server.registry.select_provider(
                "tiny:fo").peer_key == p2.identity.public_hex
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_session_invalidated_when_provider_dies(self):
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server2")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            client = SymmetryClient(Identity.from_name("fo-cli2"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            assert details.peer_key == p1.identity.public_hex
            assert server.registry.session_valid(details.session_id)

            await p1.stop(drain_timeout_s=0)
            await asyncio.sleep(0.1)  # server sees the disconnect
            assert not server.registry.session_valid(details.session_id)

            # re-request with the dead provider excluded → p2
            details2 = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo",
                exclude=[details.peer_key])
            assert details2.peer_key == p2.identity.public_hex
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_busy_shed_fails_over_to_second_provider(self):
        """Bounded-latency admission: a provider over its queue_limit
        rejects with a structured busy error instead of queueing
        unboundedly, and chat_failover completes on another provider."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server4")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            # p1 sheds everything: zero slots, zero queue.
            p1.backend.slots = 0
            p1.backend.queue_limit = 0
            client = SymmetryClient(Identity.from_name("fo-cli4"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": "busy path"}]):
                events.append(item)

            restarts = [e for e in events if isinstance(e, ChatRestart)]
            assert len(restarts) == 1
            assert restarts[0].provider_key == p2.identity.public_hex
            assert "".join(e for e in events
                           if isinstance(e, str)) == "busy path"
            assert p1.metrics["shed"] == 1
            assert p1.stats()["shed"] == 1
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_busy_raises_structured_error_direct(self):
        """A non-failover client sees ProviderBusyError carrying the
        provider's queue depth/limit, not a generic failure."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server5")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            p1.backend.slots = 0
            p1.backend.queue_limit = 0
            client = SymmetryClient(Identity.from_name("fo-cli5"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            assert details.peer_key == p1.identity.public_hex
            session = await client.connect(details)
            try:
                with pytest.raises(ProviderBusyError) as exc_info:
                    async for _ in session.chat(
                            [{"role": "user", "content": "x"}]):
                        pass
                assert exc_info.value.queue_limit == 0
            finally:
                await session.close()
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_ttft_bound_estimator_and_shed_reasons(self):
        """The two admission bounds, exercised directly: the in-flight
        queue_limit and the rate-based estimated first-token wait."""
        import time as _t

        prov = SymmetryProvider(
            provider_config("00" * 32, "est-p"), transport=MemoryTransport(),
            identity=Identity.from_name("est-p"), server_address="mem://x")

        # Nothing waiting → zero wait, no shed.
        assert prov._estimated_first_token_wait_s() == 0.0
        assert prov._admission_shed_reason() is None

        # Backlog but NO recent rate signal (burst from idle): the
        # estimator must return None and the bound must not shed.
        prov.backend.admission_ttft_bound_s = 1.0
        prov._unstarted = 50
        assert prov._estimated_first_token_wait_s() is None
        assert prov._admission_shed_reason() is None

        # Recent first tokens at ~1/s with 50 waiting → ~50 s estimated
        # wait → over the 1 s bound → structured shed reason.
        now = _t.monotonic()
        prov._first_token_stamps.extend(now - 5 + i for i in range(5))
        est = prov._estimated_first_token_wait_s()
        assert est is not None and 25 <= est <= 100
        reason = prov._admission_shed_reason()
        assert reason is not None
        assert reason["estimatedWaitS"] == round(est, 2)
        assert reason["queueDepth"] == 50

        # The in-flight bound fires first when both trip.
        prov.backend.queue_limit = 4
        prov.backend.slots = 2
        prov._in_flight = 4
        reason = prov._admission_shed_reason()
        assert reason is not None and reason["queueLimit"] == 4
        assert reason["queueDepth"] == 2  # 4 in flight - 2 slots

    def test_restarting_shed_fails_over_to_second_provider(self):
        """An engine-host restart mid-service is the structured
        {"restarting": true} shed: chat_failover treats it like a busy
        shed (fail over NOW, provider not excluded as dead) and the
        request completes on the survivor."""
        class RestartingBackend(InferenceBackend):
            name = "restarting"

            async def stream(self, request):
                raise BackendRestartingError("engine host restarting",
                                             retry_after_s=0.25)
                yield  # pragma: no cover — makes this an async generator

        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server6")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            p1.backend = RestartingBackend()
            client = SymmetryClient(Identity.from_name("fo-cli6"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": "restart path"}]):
                events.append(item)

            restarts = [e for e in events if isinstance(e, ChatRestart)]
            assert len(restarts) == 1
            assert restarts[0].provider_key == p2.identity.public_hex
            assert "".join(e for e in events
                           if isinstance(e, str)) == "restart path"
            assert p1.metrics["errors"] == 1
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_restarting_raises_structured_error_direct(self):
        """A non-failover client sees ProviderRestartingError (a
        ProviderBusyError subclass — same backoff machinery) carrying
        the provider's retry_after hint."""
        class RestartingBackend(InferenceBackend):
            name = "restarting"

            async def stream(self, request):
                raise BackendRestartingError("engine host restarting",
                                             retry_after_s=1.5)
                yield  # pragma: no cover

        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server7")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            p1.backend = RestartingBackend()
            client = SymmetryClient(Identity.from_name("fo-cli7"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            assert details.peer_key == p1.identity.public_hex
            session = await client.connect(details)
            try:
                with pytest.raises(ProviderRestartingError) as exc_info:
                    async for _ in session.chat(
                            [{"role": "user", "content": "x"}]):
                        pass
                assert exc_info.value.retry_after_s == 1.5
                assert isinstance(exc_info.value, ProviderBusyError)
            finally:
                await session.close()
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_draining_provider_sheds_structurally_and_fails_over(self):
        """provider.py used to refuse new connections while draining by
        silently closing them — the dialer hung in its handshake until a
        timeout. Now the refusal is a structured busy/draining shed after
        a completed handshake: a direct client fails FAST with a
        retryable error, and chat_failover completes on the survivor."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server8")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            client = SymmetryClient(Identity.from_name("fo-cli8"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            p1._draining = True  # drain began; in-flight would continue

            # Direct: the refusal must arrive fast and be retryable —
            # the structured busy/draining error, or (if the close
            # outraces the client's send) a gone/connection error; never
            # a silent multi-second hang.
            t0 = time.monotonic()
            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            assert details.peer_key == p1.identity.public_hex
            with pytest.raises((ProviderBusyError, ProviderGoneError,
                                ConnectionError, OSError)):
                session = await client.connect(details)
                try:
                    async for _ in session.chat(
                            [{"role": "user", "content": "x"}]):
                        pass
                finally:
                    await session.close()
            assert time.monotonic() - t0 < 5.0
            assert p1.metrics["shed"] >= 1

            # Failover: the draining provider costs one fast attempt.
            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": "drain path"}]):
                events.append(item)
            assert "".join(e for e in events
                           if isinstance(e, str)) == "drain path"
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_expired_deadline_shed_is_terminal_not_retried(self):
        """deadline_s <= 0 on arrival: the provider sheds with the
        structured expired error, the client raises the non-retryable
        DeadlineExceededError, and failover does NOT burn the second
        provider on an answer nobody awaits."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server9")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            client = SymmetryClient(Identity.from_name("fo-cli9"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            session = await client.connect(details)
            try:
                with pytest.raises(DeadlineExceededError):
                    async for _ in session.chat(
                            [{"role": "user", "content": "x"}],
                            deadline_s=0):
                        pass
            finally:
                await session.close()
            assert p1.metrics["shed"] == 1

            with pytest.raises(DeadlineExceededError):
                async for _ in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": "x"}], deadline_s=0):
                    pass
            assert p2.metrics["requests"] == 0  # never failed over
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_busy_retry_rounds_zero_disables_retry(self):
        """The retry-round cap: busy_retry_rounds=0 fails a fully-shed
        pool after ONE round (2 sheds), where the default would come
        back for a second."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server10")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            for prov in (p1, p2):
                prov.backend.slots = 0
                prov.backend.queue_limit = 0
            client = SymmetryClient(Identity.from_name("fo-cli10"), hub)

            with pytest.raises(ClientError, match="chat failed"):
                async for _ in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": "x"}],
                        busy_retry_rounds=0):
                    pass
            assert p1.metrics["shed"] + p2.metrics["shed"] == 2

            with pytest.raises(ClientError, match="chat failed"):
                async for _ in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": "x"}]):
                    pass
            # default: one jittered retry round re-tried both providers
            assert p1.metrics["shed"] + p2.metrics["shed"] == 4
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_failover_exhaustion_raises(self):
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server3")
            server = SymmetryServer(ident, hub, ping_interval_s=30.0)
            await server.start("mem://server")
            client = SymmetryClient(Identity.from_name("fo-cli3"), hub)
            with pytest.raises(ClientError, match="chat failed"):
                async for _ in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:none",
                        [{"role": "user", "content": "x"}]):
                    pass
            await server.stop()

        run(main())


class TestBusyRetryBackoff:
    """The jittered backoff formula (client.busy_retry_backoff): herd
    desynchronization is load-bearing for recovering providers, so the
    bounds are pinned."""

    def test_jitter_bounds(self):
        lo = busy_retry_backoff(4, 4, rand=lambda: 0.0)
        hi = busy_retry_backoff(4, 4, rand=lambda: 1.0)
        assert lo == pytest.approx(0.25)   # 0.5 × base 0.5
        assert hi == pytest.approx(0.75)   # 1.5 × base 0.5
        # jitter actually varies across calls with the real RNG
        vals = {round(busy_retry_backoff(4, 4), 6) for _ in range(16)}
        assert len(vals) > 1

    def test_round_escalation_doubles_base_with_ceiling(self):
        r0 = busy_retry_backoff(4, 4, round_idx=0, rand=lambda: 0.5)
        r1 = busy_retry_backoff(4, 4, round_idx=1, rand=lambda: 0.5)
        assert r1 == pytest.approx(2 * r0)
        # escalation is capped: many-round persistence must not become
        # quarter-hour sleeps
        r9 = busy_retry_backoff(4, 4, round_idx=9, rand=lambda: 0.5)
        assert r9 == pytest.approx(
            busy_retry_backoff(4, 4, round_idx=4, rand=lambda: 0.5))
        assert r9 <= 32.0

    def test_retry_after_hint_is_a_hard_floor(self):
        # The hint is ADDED under the jittered wait — even minimal
        # jitter can never schedule the retry before the provider's own
        # respawn ETA (that retry would be shed with certainty).
        v = busy_retry_backoff(0, 4, retry_after_s=3.0, rand=lambda: 0.0)
        assert v >= 3.0
        assert v == pytest.approx(3.125)  # 3.0 + 0.5 × base 0.25

    def test_depth_scales_and_caps(self):
        shallow = busy_retry_backoff(0, 8, rand=lambda: 0.5)
        deep = busy_retry_backoff(800, 8, rand=lambda: 0.5)
        assert shallow < deep <= 2.0  # capped base, never a self-stall

    def test_retry_after_hint_clamps_round_doubling(self):
        """Resume rounds must honor a restarting provider's hint, not
        amplify it: with retryAfterS present the per-round doubling is
        clamped to the round-0 base — the wait at round 3 equals the
        wait at round 0 plus the hint, instead of 8x the base on top."""
        r0 = busy_retry_backoff(4, 4, round_idx=0, retry_after_s=2.0,
                                rand=lambda: 0.5)
        r3 = busy_retry_backoff(4, 4, round_idx=3, retry_after_s=2.0,
                                rand=lambda: 0.5)
        assert r3 == pytest.approx(r0)
        assert r3 == pytest.approx(2.0 + 0.5)  # hint + un-doubled base
        # without the hint the same round still doubles (depth is the
        # only signal there)
        assert busy_retry_backoff(4, 4, round_idx=3, rand=lambda: 0.5) \
            == pytest.approx(8 * 0.5)


class PartialEchoBackend(InferenceBackend):
    """Echo that dies mid-stream: streams the first `die_after` words of
    the prompt, then raises the restarting shed — the mid-stream failure
    whose emitted text IS a prefix of a healthy echo's completion, so a
    resume on a survivor must splice byte-identically. With die_after
    beyond the prompt it is just a slow resumable echo (the hard-drop
    tests kill the connection from outside instead)."""

    name = "partial-echo"
    supports_resume = True

    def __init__(self, die_after=3, delay=0.01) -> None:
        self._die_after = die_after
        self._delay = delay

    async def start(self) -> None: ...

    async def stop(self) -> None: ...

    async def healthy(self) -> bool:
        return True

    async def stream(self, request):
        last_user = ""
        for m in reversed(request.messages):
            if m.get("role") == "user":
                last_user = m.get("content", "")
                break
        words = last_user.split(" ")
        skip_chars = len(request.resume_text or "")
        for i, word in enumerate(words):
            if i >= self._die_after:
                raise BackendRestartingError(
                    "engine host restarting", retry_after_s=0.01)
            token = word if i == 0 else " " + word
            if skip_chars >= len(token):
                skip_chars -= len(token)
                continue
            await asyncio.sleep(self._delay)
            yield StreamChunk(
                raw=f"data: {{\"choices\": [{{\"delta\": "
                    f"{{\"content\": \"{token}\"}}}}]}}",
                text=token, tokens=1)


class NoResumeEchoBackend(InferenceBackend):
    """Healthy echo that does NOT support resumption (the proxy-backend
    shape): the provider must REFUSE a resume against it and the client
    must fall back to a from-scratch restart."""

    name = "no-resume-echo"
    supports_resume = False

    async def start(self) -> None: ...

    async def stop(self) -> None: ...

    async def healthy(self) -> bool:
        return True

    async def stream(self, request):
        last_user = ""
        for m in reversed(request.messages):
            if m.get("role") == "user":
                last_user = m.get("content", "")
                break
        for i, word in enumerate(last_user.split(" ")):
            token = word if i == 0 else " " + word
            yield StreamChunk(
                raw=f"data: {{\"choices\": [{{\"delta\": "
                    f"{{\"content\": \"{token}\"}}}}]}}",
                text=token, tokens=1)


class TestResumeFailover:
    """The tentpole: a mid-stream retryable failure CONTINUES on the
    next provider from the last received token — ChatResume, spliced
    byte-identical, never a discarded partial."""

    PROMPT = "resumable streams splice the continuation byte exact"

    async def _network(self, hub, ident, p1_backend, p2_backend=None):
        server = SymmetryServer(ident, hub, ping_interval_s=30.0)
        await server.start("mem://server")
        p1 = SymmetryProvider(
            provider_config(ident.public_hex, "re-p1"), transport=hub,
            identity=Identity.from_name("re-p1"), backend=p1_backend,
            server_address="mem://server")
        await p1.start("mem://re-p1")
        await p1.wait_registered()
        p2 = SymmetryProvider(
            provider_config(ident.public_hex, "re-p2"), transport=hub,
            identity=Identity.from_name("re-p2"), backend=p2_backend,
            server_address="mem://server")
        await p2.start("mem://re-p2")
        await p2.wait_registered()
        server.registry.set_connections(p2.identity.public_hex, 5)
        return server, p1, p2

    def test_restarting_mid_stream_resumes_on_other_peer(self):
        """Mid-stream restarting shed → resume lands on the OTHER peer
        (the dying one is excluded from the immediate round), carries
        the provider's stamped emitted count, and the spliced transcript
        equals the uninterrupted completion byte for byte."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("re-server1")
            server, p1, p2 = await self._network(
                hub, ident, PartialEchoBackend(die_after=3))
            client = SymmetryClient(Identity.from_name("re-cli1"), hub)

            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": self.PROMPT}]):
                events.append(item)

            resumes = [e for e in events if isinstance(e, ChatResume)]
            assert len(resumes) == 1, events
            assert not any(isinstance(e, ChatRestart) for e in events)
            # satellite: the resume landed on a DIFFERENT peer
            assert resumes[0].provider_key == p2.identity.public_hex
            # the shed's journal-stamped count rode through: 3 words
            assert resumes[0].resumed_tokens == 3
            final = "".join(e for e in events if isinstance(e, str))
            assert final == self.PROMPT, final
            # and the splice duplicated nothing: pre-cut + post-cut
            cut = events.index(resumes[0])
            pre = "".join(e for e in events[:cut] if isinstance(e, str))
            post = "".join(e for e in events[cut:] if isinstance(e, str))
            assert pre + post == self.PROMPT
            assert pre  # the failure really was mid-stream
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_hard_death_mid_stream_resumes(self):
        """A hard connection drop (no error frame, no token stamp):
        ProviderDiedMidStreamError carries the text, the token count is
        re-derived server-side, and the splice is still byte-identical."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("re-server2")
            server, p1, p2 = await self._network(
                hub, ident, SlowBackend(delay=0.02, n=100))
            # p1 streams w0 w1 … — NOT a prefix of p2's echo, so for
            # this test p1 must echo too: replace its backend.
            p1.backend = PartialEchoBackend(die_after=100, delay=0.02)
            client = SymmetryClient(Identity.from_name("re-cli2"), hub)

            events = []

            async def chat():
                async for item in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": self.PROMPT}]):
                    events.append(item)

            async def killer():
                while not p1._in_flight:
                    await asyncio.sleep(0.01)
                await asyncio.sleep(0.08)
                for peer in list(p1._client_peers):
                    await peer.close()
                await p1.stop(drain_timeout_s=0)

            await asyncio.gather(chat(), killer())

            resumes = [e for e in events if isinstance(e, ChatResume)]
            assert len(resumes) == 1, events
            assert resumes[0].provider_key == p2.identity.public_hex
            final = "".join(e for e in events if isinstance(e, str))
            assert final == self.PROMPT, final
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_resume_refused_falls_back_to_restart(self):
        """A survivor whose backend cannot resume (proxy shape) refuses
        the resume with a structured marker; the client falls back ONCE
        to a from-scratch restart and still completes correctly."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("re-server3")
            server, p1, p2 = await self._network(
                hub, ident, PartialEchoBackend(die_after=3),
                p2_backend=NoResumeEchoBackend())
            client = SymmetryClient(Identity.from_name("re-cli3"), hub)

            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": self.PROMPT}]):
                events.append(item)

            # one resume ATTEMPT was made and refused; the fallback
            # restart voids the partial text and regenerates whole
            restarts = [e for e in events if isinstance(e, ChatRestart)]
            assert len(restarts) == 1, events
            final = "".join(
                e for e in events[events.index(restarts[-1]) + 1:]
                if isinstance(e, str))
            assert final == self.PROMPT, final
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_resume_false_restores_legacy_restart(self):
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("re-server4")
            server, p1, p2 = await self._network(
                hub, ident, PartialEchoBackend(die_after=3))
            client = SymmetryClient(Identity.from_name("re-cli4"), hub)

            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": self.PROMPT}],
                    resume=False):
                events.append(item)

            assert any(isinstance(e, ChatRestart) for e in events)
            assert not any(isinstance(e, ChatResume) for e in events)
            restarts = [e for e in events if isinstance(e, ChatRestart)]
            final = "".join(
                e for e in events[events.index(restarts[-1]) + 1:]
                if isinstance(e, str))
            assert final == self.PROMPT
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_text_failover_splices_resume(self):
        """chat_text_failover keeps parts across a ChatResume (and the
        result equals the uninterrupted completion)."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("re-server5")
            server, p1, p2 = await self._network(
                hub, ident, PartialEchoBackend(die_after=4))
            client = SymmetryClient(Identity.from_name("re-cli5"), hub)
            text = await client.chat_text_failover(
                "mem://server", ident.public_key, "tiny:fo",
                [{"role": "user", "content": self.PROMPT}])
            assert text == self.PROMPT
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_mid_stream_errors_carry_emitted_state(self):
        """Direct-session contract: ProviderRestartingError mid-stream
        carries the emitted text + the provider's stamped token count;
        ProviderDiedMidStreamError (hard drop) carries the text with
        tokens None."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("re-server6")
            server, p1, p2 = await self._network(
                hub, ident, PartialEchoBackend(die_after=2))
            client = SymmetryClient(Identity.from_name("re-cli6"), hub)
            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo",
                exclude=[p2.identity.public_hex])
            assert details.peer_key == p1.identity.public_hex
            session = await client.connect(details)
            got = []
            with pytest.raises(ProviderRestartingError) as exc_info:
                async for d in session.chat(
                        [{"role": "user", "content": self.PROMPT}]):
                    got.append(d)
            await session.close()
            exc = exc_info.value
            assert exc.emitted_text == "".join(got)
            assert exc.emitted_tokens == 2
            assert exc.emitted_text == "resumable streams"
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_hard_drop_direct_session_raises_died_mid_stream(self):
        """A connection that just dies mid-stream (no error frame at
        all) surfaces as ProviderDiedMidStreamError with the received
        text and tokens None (nothing stamped it)."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("re-server7")
            server, p1, p2 = await self._network(
                hub, ident, PartialEchoBackend(die_after=100, delay=0.03))
            client = SymmetryClient(Identity.from_name("re-cli7"), hub)
            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo",
                exclude=[p2.identity.public_hex])
            session = await client.connect(details)
            got = []

            async def chat():
                with pytest.raises(ProviderDiedMidStreamError) as ei:
                    async for d in session.chat(
                            [{"role": "user", "content": self.PROMPT}]):
                        got.append(d)
                assert ei.value.emitted_text == "".join(got)
                assert ei.value.emitted_tokens is None
                assert got, "drop landed before anything streamed"

            async def killer():
                while len(got) < 2:
                    await asyncio.sleep(0.01)
                for peer in list(p1._client_peers):
                    await peer.close()

            await asyncio.gather(chat(), killer())
            await session.close()
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())
