"""Failover: provider dies mid-stream → session requeue + client retry.

Round-2 verdict gap: the server marked dead providers offline but their
in-flight sessions just died and clients had no recovery. Now the server
expires a dead provider's sessions (registry.invalidate_sessions_for) and
SymmetryClient.chat_failover re-requests a provider with the dead one
excluded, completing the chat on the survivor (SURVEY §5.3).
"""

import asyncio
import time

import pytest

from symmetry_tpu.client.client import (
    ChatRestart,
    ClientError,
    DeadlineExceededError,
    ProviderBusyError,
    ProviderGoneError,
    ProviderRestartingError,
    SymmetryClient,
    busy_retry_backoff,
)
from symmetry_tpu.identity import Identity
from symmetry_tpu.provider.backends.base import (
    BackendRestartingError,
    InferenceBackend,
    StreamChunk,
)
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.server.broker import SymmetryServer
from symmetry_tpu.transport.memory import MemoryTransport


def run(coro):
    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(coro, 60))


class SlowBackend(InferenceBackend):
    """Streams one word per tick forever-ish — guarantees the kill lands
    mid-stream."""

    name = "slow"

    def __init__(self, config=None, delay=0.05, n=100) -> None:
        self._delay = delay
        self._n = n

    async def start(self) -> None: ...

    async def stop(self) -> None: ...

    async def healthy(self) -> bool:
        return True

    async def stream(self, request):
        for i in range(self._n):
            await asyncio.sleep(self._delay)
            yield StreamChunk(raw=f"data: {{\"choices\": [{{\"delta\": "
                                  f"{{\"content\": \"w{i} \"}}}}]}}",
                              text=f"w{i} ")


def provider_config(server_key_hex, name):
    return ConfigManager(config={
        "name": name, "public": True, "serverKey": server_key_hex,
        "modelName": "tiny:fo", "apiProvider": "echo",
        "dataCollectionEnabled": False,
    })


async def start_network(hub, server_ident, slow_first=True):
    server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
    await server.start("mem://server")
    p1 = SymmetryProvider(
        provider_config(server_ident.public_hex, "fo-p1"), transport=hub,
        identity=Identity.from_name("fo-p1"),
        backend=SlowBackend() if slow_first else None,
        server_address="mem://server")
    await p1.start("mem://fo-p1")
    await p1.wait_registered()
    p2 = SymmetryProvider(
        provider_config(server_ident.public_hex, "fo-p2"), transport=hub,
        identity=Identity.from_name("fo-p2"),
        server_address="mem://server")
    await p2.start("mem://fo-p2")
    await p2.wait_registered()
    return server, p1, p2


class TestFailover:
    def test_mid_stream_provider_death_completes_on_second(self):
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server")
            server, p1, p2 = await start_network(hub, ident)
            client = SymmetryClient(Identity.from_name("fo-cli"), hub)

            # The broker prefers the least-loaded provider; make p1 the
            # guaranteed first pick by marking p2 busier.
            server.registry.set_connections(
                p2.identity.public_hex, 5)

            events = []

            async def chat():
                async for item in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": "failover!"}]):
                    events.append(item)

            async def killer():
                # wait until p1 is actually streaming, then hard-kill it
                while not p1._in_flight:
                    await asyncio.sleep(0.01)
                await asyncio.sleep(0.15)
                for peer in list(p1._client_peers):
                    await peer.close()
                await p1.stop(drain_timeout_s=0)

            await asyncio.gather(chat(), killer())

            restarts = [e for e in events if isinstance(e, ChatRestart)]
            assert len(restarts) == 1
            assert restarts[0].provider_key == p2.identity.public_hex
            # deltas after the restart come from p2's echo backend
            after = events[events.index(restarts[0]) + 1:]
            assert after and all(isinstance(d, str) for d in after)
            # p1's session is dead server-side
            assert server.registry.select_provider(
                "tiny:fo").peer_key == p2.identity.public_hex
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_session_invalidated_when_provider_dies(self):
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server2")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            client = SymmetryClient(Identity.from_name("fo-cli2"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            assert details.peer_key == p1.identity.public_hex
            assert server.registry.session_valid(details.session_id)

            await p1.stop(drain_timeout_s=0)
            await asyncio.sleep(0.1)  # server sees the disconnect
            assert not server.registry.session_valid(details.session_id)

            # re-request with the dead provider excluded → p2
            details2 = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo",
                exclude=[details.peer_key])
            assert details2.peer_key == p2.identity.public_hex
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_busy_shed_fails_over_to_second_provider(self):
        """Bounded-latency admission: a provider over its queue_limit
        rejects with a structured busy error instead of queueing
        unboundedly, and chat_failover completes on another provider."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server4")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            # p1 sheds everything: zero slots, zero queue.
            p1.backend.slots = 0
            p1.backend.queue_limit = 0
            client = SymmetryClient(Identity.from_name("fo-cli4"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": "busy path"}]):
                events.append(item)

            restarts = [e for e in events if isinstance(e, ChatRestart)]
            assert len(restarts) == 1
            assert restarts[0].provider_key == p2.identity.public_hex
            assert "".join(e for e in events
                           if isinstance(e, str)) == "busy path"
            assert p1.metrics["shed"] == 1
            assert p1.stats()["shed"] == 1
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_busy_raises_structured_error_direct(self):
        """A non-failover client sees ProviderBusyError carrying the
        provider's queue depth/limit, not a generic failure."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server5")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            p1.backend.slots = 0
            p1.backend.queue_limit = 0
            client = SymmetryClient(Identity.from_name("fo-cli5"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            assert details.peer_key == p1.identity.public_hex
            session = await client.connect(details)
            try:
                with pytest.raises(ProviderBusyError) as exc_info:
                    async for _ in session.chat(
                            [{"role": "user", "content": "x"}]):
                        pass
                assert exc_info.value.queue_limit == 0
            finally:
                await session.close()
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_ttft_bound_estimator_and_shed_reasons(self):
        """The two admission bounds, exercised directly: the in-flight
        queue_limit and the rate-based estimated first-token wait."""
        import time as _t

        prov = SymmetryProvider(
            provider_config("00" * 32, "est-p"), transport=MemoryTransport(),
            identity=Identity.from_name("est-p"), server_address="mem://x")

        # Nothing waiting → zero wait, no shed.
        assert prov._estimated_first_token_wait_s() == 0.0
        assert prov._admission_shed_reason() is None

        # Backlog but NO recent rate signal (burst from idle): the
        # estimator must return None and the bound must not shed.
        prov.backend.admission_ttft_bound_s = 1.0
        prov._unstarted = 50
        assert prov._estimated_first_token_wait_s() is None
        assert prov._admission_shed_reason() is None

        # Recent first tokens at ~1/s with 50 waiting → ~50 s estimated
        # wait → over the 1 s bound → structured shed reason.
        now = _t.monotonic()
        prov._first_token_stamps.extend(now - 5 + i for i in range(5))
        est = prov._estimated_first_token_wait_s()
        assert est is not None and 25 <= est <= 100
        reason = prov._admission_shed_reason()
        assert reason is not None
        assert reason["estimatedWaitS"] == round(est, 2)
        assert reason["queueDepth"] == 50

        # The in-flight bound fires first when both trip.
        prov.backend.queue_limit = 4
        prov.backend.slots = 2
        prov._in_flight = 4
        reason = prov._admission_shed_reason()
        assert reason is not None and reason["queueLimit"] == 4
        assert reason["queueDepth"] == 2  # 4 in flight - 2 slots

    def test_restarting_shed_fails_over_to_second_provider(self):
        """An engine-host restart mid-service is the structured
        {"restarting": true} shed: chat_failover treats it like a busy
        shed (fail over NOW, provider not excluded as dead) and the
        request completes on the survivor."""
        class RestartingBackend(InferenceBackend):
            name = "restarting"

            async def stream(self, request):
                raise BackendRestartingError("engine host restarting",
                                             retry_after_s=0.25)
                yield  # pragma: no cover — makes this an async generator

        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server6")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            p1.backend = RestartingBackend()
            client = SymmetryClient(Identity.from_name("fo-cli6"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": "restart path"}]):
                events.append(item)

            restarts = [e for e in events if isinstance(e, ChatRestart)]
            assert len(restarts) == 1
            assert restarts[0].provider_key == p2.identity.public_hex
            assert "".join(e for e in events
                           if isinstance(e, str)) == "restart path"
            assert p1.metrics["errors"] == 1
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_restarting_raises_structured_error_direct(self):
        """A non-failover client sees ProviderRestartingError (a
        ProviderBusyError subclass — same backoff machinery) carrying
        the provider's retry_after hint."""
        class RestartingBackend(InferenceBackend):
            name = "restarting"

            async def stream(self, request):
                raise BackendRestartingError("engine host restarting",
                                             retry_after_s=1.5)
                yield  # pragma: no cover

        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server7")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            p1.backend = RestartingBackend()
            client = SymmetryClient(Identity.from_name("fo-cli7"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            assert details.peer_key == p1.identity.public_hex
            session = await client.connect(details)
            try:
                with pytest.raises(ProviderRestartingError) as exc_info:
                    async for _ in session.chat(
                            [{"role": "user", "content": "x"}]):
                        pass
                assert exc_info.value.retry_after_s == 1.5
                assert isinstance(exc_info.value, ProviderBusyError)
            finally:
                await session.close()
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_draining_provider_sheds_structurally_and_fails_over(self):
        """provider.py used to refuse new connections while draining by
        silently closing them — the dialer hung in its handshake until a
        timeout. Now the refusal is a structured busy/draining shed after
        a completed handshake: a direct client fails FAST with a
        retryable error, and chat_failover completes on the survivor."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server8")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            client = SymmetryClient(Identity.from_name("fo-cli8"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            p1._draining = True  # drain began; in-flight would continue

            # Direct: the refusal must arrive fast and be retryable —
            # the structured busy/draining error, or (if the close
            # outraces the client's send) a gone/connection error; never
            # a silent multi-second hang.
            t0 = time.monotonic()
            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            assert details.peer_key == p1.identity.public_hex
            with pytest.raises((ProviderBusyError, ProviderGoneError,
                                ConnectionError, OSError)):
                session = await client.connect(details)
                try:
                    async for _ in session.chat(
                            [{"role": "user", "content": "x"}]):
                        pass
                finally:
                    await session.close()
            assert time.monotonic() - t0 < 5.0
            assert p1.metrics["shed"] >= 1

            # Failover: the draining provider costs one fast attempt.
            events = []
            async for item in client.chat_failover(
                    "mem://server", ident.public_key, "tiny:fo",
                    [{"role": "user", "content": "drain path"}]):
                events.append(item)
            assert "".join(e for e in events
                           if isinstance(e, str)) == "drain path"
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_expired_deadline_shed_is_terminal_not_retried(self):
        """deadline_s <= 0 on arrival: the provider sheds with the
        structured expired error, the client raises the non-retryable
        DeadlineExceededError, and failover does NOT burn the second
        provider on an answer nobody awaits."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server9")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            client = SymmetryClient(Identity.from_name("fo-cli9"), hub)
            server.registry.set_connections(p2.identity.public_hex, 5)

            details = await client.request_provider(
                "mem://server", ident.public_key, "tiny:fo")
            session = await client.connect(details)
            try:
                with pytest.raises(DeadlineExceededError):
                    async for _ in session.chat(
                            [{"role": "user", "content": "x"}],
                            deadline_s=0):
                        pass
            finally:
                await session.close()
            assert p1.metrics["shed"] == 1

            with pytest.raises(DeadlineExceededError):
                async for _ in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": "x"}], deadline_s=0):
                    pass
            assert p2.metrics["requests"] == 0  # never failed over
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_busy_retry_rounds_zero_disables_retry(self):
        """The retry-round cap: busy_retry_rounds=0 fails a fully-shed
        pool after ONE round (2 sheds), where the default would come
        back for a second."""
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server10")
            server, p1, p2 = await start_network(hub, ident,
                                                 slow_first=False)
            for prov in (p1, p2):
                prov.backend.slots = 0
                prov.backend.queue_limit = 0
            client = SymmetryClient(Identity.from_name("fo-cli10"), hub)

            with pytest.raises(ClientError, match="chat failed"):
                async for _ in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": "x"}],
                        busy_retry_rounds=0):
                    pass
            assert p1.metrics["shed"] + p2.metrics["shed"] == 2

            with pytest.raises(ClientError, match="chat failed"):
                async for _ in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:fo",
                        [{"role": "user", "content": "x"}]):
                    pass
            # default: one jittered retry round re-tried both providers
            assert p1.metrics["shed"] + p2.metrics["shed"] == 4
            await p1.stop(drain_timeout_s=1)
            await p2.stop(drain_timeout_s=1)
            await server.stop()

        run(main())

    def test_failover_exhaustion_raises(self):
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("fo-server3")
            server = SymmetryServer(ident, hub, ping_interval_s=30.0)
            await server.start("mem://server")
            client = SymmetryClient(Identity.from_name("fo-cli3"), hub)
            with pytest.raises(ClientError, match="chat failed"):
                async for _ in client.chat_failover(
                        "mem://server", ident.public_key, "tiny:none",
                        [{"role": "user", "content": "x"}]):
                    pass
            await server.stop()

        run(main())


class TestBusyRetryBackoff:
    """The jittered backoff formula (client.busy_retry_backoff): herd
    desynchronization is load-bearing for recovering providers, so the
    bounds are pinned."""

    def test_jitter_bounds(self):
        lo = busy_retry_backoff(4, 4, rand=lambda: 0.0)
        hi = busy_retry_backoff(4, 4, rand=lambda: 1.0)
        assert lo == pytest.approx(0.25)   # 0.5 × base 0.5
        assert hi == pytest.approx(0.75)   # 1.5 × base 0.5
        # jitter actually varies across calls with the real RNG
        vals = {round(busy_retry_backoff(4, 4), 6) for _ in range(16)}
        assert len(vals) > 1

    def test_round_escalation_doubles_base_with_ceiling(self):
        r0 = busy_retry_backoff(4, 4, round_idx=0, rand=lambda: 0.5)
        r1 = busy_retry_backoff(4, 4, round_idx=1, rand=lambda: 0.5)
        assert r1 == pytest.approx(2 * r0)
        # escalation is capped: many-round persistence must not become
        # quarter-hour sleeps
        r9 = busy_retry_backoff(4, 4, round_idx=9, rand=lambda: 0.5)
        assert r9 == pytest.approx(
            busy_retry_backoff(4, 4, round_idx=4, rand=lambda: 0.5))
        assert r9 <= 32.0

    def test_retry_after_hint_is_a_hard_floor(self):
        # The hint is ADDED under the jittered wait — even minimal
        # jitter can never schedule the retry before the provider's own
        # respawn ETA (that retry would be shed with certainty).
        v = busy_retry_backoff(0, 4, retry_after_s=3.0, rand=lambda: 0.0)
        assert v >= 3.0
        assert v == pytest.approx(3.125)  # 3.0 + 0.5 × base 0.25

    def test_depth_scales_and_caps(self):
        shallow = busy_retry_backoff(0, 8, rand=lambda: 0.5)
        deep = busy_retry_backoff(800, 8, rand=lambda: 0.5)
        assert shallow < deep <= 2.0  # capped base, never a self-stall
