"""MoE model family (models/moe.py): routing, forward, engine, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import forward, init_cache, init_params, preset
from symmetry_tpu.models.llama import (
    MoEConfig,
    param_logical_axes,
    quantize_params,
)
from symmetry_tpu.models.moe import route_top_k


class TestRouting:
    def test_gates_topk_normalized(self):
        logits = jax.random.normal(jax.random.key(0), (2, 3, 8))
        gates = np.asarray(route_top_k(logits, 2))
        # exactly k nonzero per token, summing to 1
        nonzero = (gates > 0).sum(-1)
        np.testing.assert_array_equal(nonzero, np.full((2, 3), 2))
        np.testing.assert_allclose(gates.sum(-1), 1.0, rtol=1e-5)

    def test_gates_pick_largest(self):
        logits = jnp.asarray([[[1.0, 5.0, 3.0, -2.0]]])
        gates = np.asarray(route_top_k(logits, 2))[0, 0]
        assert gates[1] > gates[2] > 0
        assert gates[0] == 0 and gates[3] == 0


class TestMoEForward:
    def test_forward_and_greedy_decode(self):
        cfg = preset("tiny-moe")
        assert isinstance(cfg, MoEConfig)
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        assert params["layers"]["wg"].shape == (2, 4, 64, 128)
        assert params["layers"]["router"].shape == (2, 64, 4)

        cache = init_cache(cfg, 1, 32, jnp.float32)
        tokens = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
        logits, cache = forward(params, cfg, tokens, cache)
        assert logits.shape == (1, 4, 512)
        assert np.isfinite(np.asarray(logits)).all()
        # decode continues from the cache
        last = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        logits2, cache = forward(params, cfg, last[:, None], cache)
        assert logits2.shape == (1, 1, 512)
        assert int(cache.lengths[0]) == 5

    def test_quantized_matches_dense_approximately(self):
        cfg = preset("tiny-moe")
        params = init_params(cfg, jax.random.key(1), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 512, (1, 8)), jnp.int32)
        dense, _ = forward(params, cfg, tokens,
                           init_cache(cfg, 1, 16, jnp.float32))
        qparams = quantize_params(jax.tree.map(lambda a: a, params))
        quant, _ = forward(qparams, cfg, tokens,
                           init_cache(cfg, 1, 16, jnp.float32))
        d, q = np.asarray(dense[:, -1]), np.asarray(quant[:, -1])
        assert np.abs(d - q).max() <= 0.05 * np.abs(d).max() + 0.05

    def test_engine_serves_moe(self):
        cfg = preset("tiny-moe")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        eng = InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                              max_seq_len=64, prefill_buckets=(16,),
                              cache_dtype=jnp.float32)
        first = eng.prefill_and_insert(0, list(b"moe prompt"),
                                       SamplingParams())
        toks = eng.decode_step()
        assert toks.shape == (2,)
        assert 0 <= first < cfg.vocab_size

    def test_engine_greedy_deterministic_across_slots(self):
        """Continuous-batch invariance holds for MoE too: slot 1's traffic
        must not perturb slot 0's greedy tokens."""
        cfg = preset("tiny-moe")
        params = init_params(cfg, jax.random.key(0), jnp.float32)

        def solo():
            eng = InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                                  max_seq_len=64, prefill_buckets=(16,),
                                  cache_dtype=jnp.float32)
            out = [eng.prefill_and_insert(0, list(b"abc"), SamplingParams())]
            for _ in range(5):
                out.append(int(eng.decode_step()[0]))
            return out

        def batched():
            eng = InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                                  max_seq_len=64, prefill_buckets=(16,),
                                  cache_dtype=jnp.float32)
            out = [eng.prefill_and_insert(0, list(b"abc"), SamplingParams())]
            eng.prefill_and_insert(1, list(b"other stream"), SamplingParams())
            for _ in range(5):
                out.append(int(eng.decode_step()[0]))
            return out

        assert solo() == batched()


class TestExpertParallel:
    def test_ep_sharded_forward_matches_unsharded(self):
        """(expert=2, model=2, data=2) mesh over 8 virtual CPU devices:
        EP+TP+DP sharded forward must equal the single-device result."""
        from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for

        cfg = preset("tiny-moe")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 512, (2, 8)), jnp.int32)
        want, _ = forward(params, cfg, tokens,
                          init_cache(cfg, 2, 16, jnp.float32))

        mesh = build_mesh(MeshSpec(data=2, expert=2, model=2))
        sharded = jax.device_put(
            params, shardings_for(param_logical_axes(cfg), mesh))

        @jax.jit
        def run(p, t):
            logits, _ = forward(p, cfg, t, init_cache(cfg, 2, 16, jnp.float32))
            return logits

        got = run(sharded, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


class TestMoECheckpoint:
    def test_save_load_roundtrip_streaming(self, tmp_path):
        """tiny-moe params → HF mixtral-layout safetensors → streaming
        loader → identical forward logits."""
        pytest.importorskip("safetensors")
        from symmetry_tpu.engine.weights import load_checkpoint, save_checkpoint

        cfg = preset("tiny-moe")
        params = init_params(cfg, jax.random.key(2), jnp.float32)
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, params, cfg)

        loaded, loaded_cfg = load_checkpoint(path, dtype=jnp.float32)
        assert getattr(loaded_cfg, "num_experts", 0) == 4
        assert loaded["layers"]["wg"].shape == (2, 4, 64, 128)

        tokens = jnp.asarray(
            np.random.default_rng(2).integers(0, 512, (1, 6)), jnp.int32)
        want, _ = forward(params, cfg, tokens,
                          init_cache(cfg, 1, 16, jnp.float32))
        got, _ = forward(loaded, loaded_cfg, tokens,
                         init_cache(cfg, 1, 16, jnp.float32))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_convert_hf_state_dict_moe(self, tmp_path):
        pytest.importorskip("safetensors")
        from safetensors.numpy import load_file

        from symmetry_tpu.engine.weights import (
            convert_hf_state_dict, save_checkpoint)

        cfg = preset("tiny-moe")
        params = init_params(cfg, jax.random.key(3), jnp.float32)
        path = str(tmp_path / "ckpt2")
        save_checkpoint(path, params, cfg)
        tensors = load_file(path + "/model.safetensors")
        assert any("block_sparse_moe.experts" in n for n in tensors)

        converted = convert_hf_state_dict(tensors, cfg)
        np.testing.assert_allclose(
            converted["layers"]["router"],
            np.asarray(params["layers"]["router"], np.float32), rtol=1e-6)
        np.testing.assert_allclose(
            converted["layers"]["wd"],
            np.asarray(params["layers"]["wd"], np.float32), rtol=1e-6)


class TestDispatchPrefill:
    """Capacity-factor token dispatch (moe_mlp_dispatch): prefill computes
    top_k*cf/num_experts of the dense-mixture FLOPs; with capacity high
    enough for zero drops it must match the dense mixture EXACTLY."""

    def _layer_params(self, cfg, key):
        from symmetry_tpu.models.llama import init_params

        params = init_params(cfg, key, jnp.float32)
        lp = {k: v[0] for k, v in params["layers"].items()}
        return lp

    def test_no_drop_dispatch_matches_dense(self):
        import dataclasses

        from symmetry_tpu.models.moe import moe_mlp, moe_mlp_dispatch

        cfg = preset("tiny-moe")
        lp = self._layer_params(cfg, jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (4, 32, cfg.hidden_size),
                              jnp.float32)
        # capacity X/k => C = T: nothing can drop
        full = dataclasses.replace(
            cfg, moe_capacity_factor=cfg.num_experts
            / cfg.num_experts_per_tok)
        got = moe_mlp_dispatch(x, lp, full)
        # dense path: call with S=1 slices to force the dense branch
        dense = moe_mlp(x[:, :1], lp, cfg)
        np.testing.assert_allclose(np.asarray(got[:, :1]),
                                   np.asarray(dense), rtol=2e-4, atol=2e-4)
        # and over the full sequence against a manual dense reference
        from symmetry_tpu.models.moe import qmatmul_experts, route_top_k

        gates = route_top_k(
            jnp.asarray(x @ lp["router"], jnp.float32),
            cfg.num_experts_per_tok).astype(x.dtype)
        h = jax.nn.silu(qmatmul_experts(x, lp["wg"])) * qmatmul_experts(
            x, lp["wu"])
        y = jnp.einsum("bsxf,xfe->bsxe", h, lp["wd"])
        want = jnp.einsum("bsxe,bsx->bse", y, gates)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_low_capacity_drops_but_stays_finite(self):
        import dataclasses

        from symmetry_tpu.models.moe import moe_mlp_dispatch

        cfg = dataclasses.replace(preset("tiny-moe"),
                                  moe_capacity_factor=0.5)
        lp = self._layer_params(cfg, jax.random.key(2))
        x = jax.random.normal(jax.random.key(3), (2, 64, cfg.hidden_size),
                              jnp.float32)
        out = moe_mlp_dispatch(x, lp, cfg)
        assert out.shape == x.shape
        assert bool(jnp.isfinite(out).all())

    def test_prefill_routes_through_dispatch_and_decode_stays_dense(self):
        """forward() at S>64 tokens uses the dispatch path; greedy decode
        continuations still match the dense engine reference (decode is
        S=1 => dense mixture, and the prefill numerics stay exact with
        no-drop capacity)."""
        import dataclasses

        cfg = dataclasses.replace(
            preset("tiny-moe"),
            moe_capacity_factor=(preset("tiny-moe").num_experts
                                 / preset("tiny-moe").num_experts_per_tok))
        params = init_params(cfg, jax.random.key(4), jnp.float32)
        prompt = list(range(1, 97))  # 96 tokens >= MIN_DISPATCH_TOKENS

        cache = init_cache(cfg, 1, 128, jnp.float32)
        logits, cache = forward(params, cfg,
                                jnp.asarray([prompt], jnp.int32), cache)
        toks = [int(jnp.argmax(logits[0, -1]))]
        for _ in range(4):
            logits, cache = forward(
                params, cfg, jnp.asarray([[toks[-1]]], jnp.int32), cache)
            toks.append(int(jnp.argmax(logits[0, 0])))

        # engine path (bucketed prefill + slot decode) agrees
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=128,
            prefill_buckets=(128,), cache_dtype=jnp.float32)
        first = engine.prefill_and_insert(0, prompt, SamplingParams())
        got = [first]
        for _ in range(4):
            got.append(int(engine.decode_step()[0]))
        assert got == toks
