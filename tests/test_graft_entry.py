"""Regression tests for the driver entry points (__graft_entry__.py).

The driver invokes dryrun_multichip in a fresh process whose default JAX
backend may be a single real TPU chip (no JAX_PLATFORMS/XLA_FLAGS set).
Round-1 failure mode: the function trusted the ambient backend and asserted
"need 8 devices, have 1". These tests replicate that bare environment in a
subprocess and require the function to self-pin a virtual CPU mesh.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # multi-process / heavy-compile; run with -m ""

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bare_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = REPO
    return env


@pytest.mark.slow
def test_dryrun_multichip_bare_env():
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip\n"
         "dryrun_multichip(8)\n"
         "print('MULTICHIP_OK')"],
        cwd=REPO, env=_bare_env(), capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "MULTICHIP_OK" in proc.stdout


@pytest.mark.slow
def test_entry_compiles():
    # entry() must return (fn, args) with fn jittable on the default backend.
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax\n"
         "from __graft_entry__ import entry\n"
         "fn, args = entry()\n"
         "jax.jit(fn).lower(*args).compile()\n"
         "print('ENTRY_OK')"],
        cwd=REPO, env={**os.environ, "PYTHONPATH": REPO},
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ENTRY_OK" in proc.stdout
