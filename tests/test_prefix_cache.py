"""Shared-prefix KV cache: prefill each common prefix once, admit many.

Covers the acceptance surface of the prefix-cache PR:

  - exact hit: a repeated prompt pays ZERO full-prefill dispatches — the
    cached portion is copied, only the (>= 1 token) suffix runs
  - partial hit: prompts sharing an aligned prefix prefill suffix-only,
    and any aligned sub-boundary of a longer entry also hits
  - decode equivalence: greedy AND seeded-sampled tokens are identical
    with the cache on vs off (the cache must be invisible to outputs)
  - LRU eviction under a small byte budget, pin-while-copying (a pinned
    entry is never evicted), and budget-rejection of oversized entries
  - scheduler integration: hit/miss requests partition into separate
    dispatch units inside _place_group and streams match the sequential
    reference; counters flow through scheduler.stats()
"""

import threading

import jax
import jax.numpy as jnp
import pytest

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.prefix_cache import PrefixStore
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import forward, init_cache, init_params, preset


@pytest.fixture(scope="module")
def setup():
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    return cfg, params


def make_engine(cfg, params, slots=4, cache_mb=16, chunk=8,
                buckets=(16, 32)):
    return InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=slots, max_seq_len=64,
        prefill_buckets=buckets, cache_dtype=jnp.float32,
        prefill_chunk=chunk, prefix_cache_bytes=cache_mb * 2**20)


def reference_greedy(cfg, params, prompt_ids, n_tokens):
    cache = init_cache(cfg, 1, 64, jnp.float32)
    tokens = jnp.asarray([prompt_ids], jnp.int32)
    logits, cache = forward(params, cfg, tokens, cache)
    out = []
    last = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    out.append(int(last[0]))
    for _ in range(n_tokens - 1):
        logits, cache = forward(params, cfg, last[:, None], cache)
        last = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
        out.append(int(last[0]))
    return out


def count_dispatches(engine):
    """Wrap the full-prefill and suffix jits with call counters."""
    counts = {"prefill": 0, "chunk_final": 0, "chunk_step": 0}
    real_prefill, real_final = engine._prefill, engine._chunk_final
    real_step = engine._chunk_step

    def prefill(*a, **kw):
        counts["prefill"] += 1
        return real_prefill(*a, **kw)

    def final(*a, **kw):
        counts["chunk_final"] += 1
        return real_final(*a, **kw)

    def step(*a, **kw):
        counts["chunk_step"] += 1
        return real_step(*a, **kw)

    engine._prefill = prefill
    engine._chunk_final = final
    engine._chunk_step = step
    return counts


BASE = list(b"hello world prefix!")  # 19 tokens -> aligned entry @ 16


class TestEngineHitPaths:
    def test_exact_hit_skips_full_prefill(self, setup):
        """Second identical prompt: zero full-prefill dispatches — one
        seed copy + one suffix dispatch covers admission."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        want = reference_greedy(cfg, params, BASE, 6)

        first = engine.prefill_and_insert(0, BASE, SamplingParams())
        got_miss = [first] + [int(engine.decode_step()[0])
                              for _ in range(5)]
        assert got_miss == want
        # (hit/miss counters tick in prefix_lookup — the scheduler's
        # admission path; the direct engine call here only stores.)
        st = engine.prefix_store.stats()
        assert st["insertions"] == 1

        counts = count_dispatches(engine)
        hit = engine.prefix_lookup(BASE)
        assert hit is not None and hit.length == 16
        firsts = engine.prefill_and_insert_cached(
            [(1, BASE, SamplingParams())], hit)
        assert counts["prefill"] == 0  # cached portion: no prefill
        assert counts["chunk_final"] == 1  # suffix-only dispatch
        got_hit = list(firsts) + [int(engine.decode_step()[1])
                                  for _ in range(5)]
        assert got_hit == want
        st = engine.prefix_store.stats()
        assert st["hits"] == 1 and st["tokens_reused"] == 16

    def test_partial_hit_suffix_only(self, setup):
        """A prompt sharing the first aligned boundary prefills only its
        own suffix and still matches the sequential reference."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())

        other = BASE[:16] + list(b"XYZ")
        want = reference_greedy(cfg, params, other, 6)
        counts = count_dispatches(engine)
        hit = engine.prefix_lookup(other)
        assert hit is not None and hit.length == 16
        firsts = engine.prefill_and_insert_cached(
            [(1, other, SamplingParams())], hit)
        assert counts["prefill"] == 0 and counts["chunk_final"] == 1
        got = list(firsts) + [int(engine.decode_step()[1])
                              for _ in range(5)]
        assert got == want

    def test_sub_boundary_of_longer_entry_hits(self, setup):
        """KV is causal: the first 8 positions of a 16-token entry ARE
        the 8-token prefix's KV, so a prompt sharing only 8 tokens still
        hits at the 8 boundary."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())

        other = BASE[:8] + list(b"tail998")
        want = reference_greedy(cfg, params, other, 4)
        hit = engine.prefix_lookup(other)
        assert hit is not None and hit.length == 8
        firsts = engine.prefill_and_insert_cached(
            [(1, other, SamplingParams())], hit)
        got = list(firsts) + [int(engine.decode_step()[1])
                              for _ in range(3)]
        assert got == want

    def test_long_suffix_runs_seeded_chunked(self, setup):
        """Suffix beyond one alignment unit: the hit seeds a chunked
        prefill instead (prefix copied, chunks cover only the suffix),
        and the finished buffer is adopted as a LONGER entry for free."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())

        prompt = BASE[:8] + list(b"different tail..")  # 24 tok, sfx 16
        want = reference_greedy(cfg, params, prompt, 4)
        hit = engine.prefix_lookup(prompt)
        assert hit is not None and hit.length == 8
        assert engine.seeded_chunk_ok(len(prompt))
        counts = count_dispatches(engine)
        job = engine.start_chunked_prefill(1, prompt, SamplingParams(),
                                           hit=hit)
        assert job.start_pos == 8 and job.suffix_len == 16
        first = None
        while first is None:
            first = engine.advance_chunked_prefill(job)
        assert counts["prefill"] == 0
        got = [first] + [int(engine.decode_step()[1]) for _ in range(3)]
        assert got == want
        # zero-copy adoption: the completed 24-aligned prefix is stored
        assert engine.prefix_store.has(prompt[:24])

    def test_coalesced_hit_group_with_pad_rows(self, setup):
        """Several requests sharing one entry admit as ONE cached unit
        (batch padded to the compiled width) and each stream matches its
        own sequential reference."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())
        prompts = [BASE[:16] + list(b"A%d" % i) for i in range(3)]
        wants = [reference_greedy(cfg, params, p, 3) for p in prompts]

        hit = engine.prefix_lookup(prompts[0])
        firsts = engine.prefill_and_insert_cached(
            [(i, p, SamplingParams()) for i, p in enumerate(prompts)], hit)
        gots = [[f] for f in firsts]
        for _ in range(2):
            toks = engine.decode_step()
            for i in range(3):
                gots[i].append(int(toks[i]))
        assert gots == wants

    def test_seeded_sampling_identical_cache_on_off(self, setup):
        """A seeded sampled request reproduces its EXACT completion
        whether admission went through the cache or a full prefill."""
        cfg, params = setup
        sp = SamplingParams(temperature=0.9, top_p=0.95, seed=42)

        engine_off = make_engine(cfg, params, cache_mb=0)
        assert engine_off.prefix_store is None
        toks_off = [engine_off.prefill_and_insert(0, BASE, sp)]
        toks_off += [int(engine_off.decode_step()[0]) for _ in range(5)]

        engine_on = make_engine(cfg, params)
        engine_on.prefill_and_insert(0, BASE, SamplingParams(seed=7))
        hit = engine_on.prefix_lookup(BASE)
        assert hit is not None
        toks_on = list(engine_on.prefill_and_insert_cached(
            [(1, BASE, sp)], hit))
        toks_on += [int(engine_on.decode_step()[1]) for _ in range(5)]
        assert toks_on == toks_off

    def test_warmup_then_hit_path(self, setup):
        """warmup() with the cache enabled (extra compile grid) must not
        perturb subsequent cached admissions."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.warmup()
        want = reference_greedy(cfg, params, BASE, 4)
        engine.prefill_and_insert(0, BASE, SamplingParams())
        hit = engine.prefix_lookup(BASE)
        firsts = engine.prefill_and_insert_cached(
            [(1, BASE, SamplingParams())], hit)
        got = list(firsts) + [int(engine.decode_step()[1])
                              for _ in range(3)]
        assert got == want


class TestStoreSemantics:
    def entry_bytes(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())
        return next(iter(engine.prefix_store._entries.values())).nbytes

    def test_lru_eviction_under_byte_budget(self, setup):
        """Budget for ~1.5 entries: the second distinct prefix evicts the
        first (LRU), counters record it, and the evicted prefix misses."""
        cfg, params = setup
        per_entry = self.entry_bytes(setup)
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=64,
            prefill_buckets=(16, 32), cache_dtype=jnp.float32,
            prefill_chunk=8, prefix_cache_bytes=int(per_entry * 1.5))
        a = list(b"prefix AAAAAAAA x")
        b = list(b"prefix BBBBBBBB x")
        engine.prefill_and_insert(0, a, SamplingParams())
        assert engine.prefix_store.has(a[:16])
        engine.prefill_and_insert(1, b, SamplingParams())
        st = engine.prefix_store.stats()
        assert st["evictions"] == 1 and st["entries"] == 1
        assert not engine.prefix_store.has(a[:16])
        assert engine.prefix_store.has(b[:16])
        hit = engine.prefix_lookup(a)
        assert hit is None
        assert engine.prefix_store.stats()["misses"] >= 1

    def test_pinned_entry_survives_eviction_pressure(self):
        """Pin-while-copying: a pinned entry is never evicted; once
        released it becomes evictable again."""
        store = PrefixStore(budget_bytes=250, align=4)
        store.insert([1, 2, 3, 4], cache="kv-a", nbytes=100)
        hit = store.lookup([1, 2, 3, 4, 9])
        assert hit is not None and hit.entry.pins == 1
        # Inserting under pressure must skip the pinned entry — and with
        # nothing evictable the insert is REJECTED, not forced over
        # budget.
        assert not store.insert([5, 6, 7, 8], cache="kv-b", nbytes=200)
        assert store.has([1, 2, 3, 4])
        st = store.stats()
        assert st["rejected"] == 1 and st["evictions"] == 0
        assert st["pinned"] == 1
        hit.release()
        hit.release()  # idempotent
        assert hit.entry.pins == 0
        assert store.insert([5, 6, 7, 8], cache="kv-b", nbytes=200)
        assert not store.has([1, 2, 3, 4])  # LRU evicted post-release
        assert store.stats()["evictions"] == 1

    def test_oversized_entry_rejected(self):
        store = PrefixStore(budget_bytes=50, align=4)
        assert not store.insert([1, 2, 3, 4], cache="kv", nbytes=100)
        assert store.stats()["rejected"] == 1 and len(store) == 0

    def test_misaligned_and_duplicate_inserts_refused(self):
        store = PrefixStore(budget_bytes=1000, align=4)
        assert not store.insert([1, 2, 3], cache="kv", nbytes=10)
        assert store.insert([1, 2, 3, 4], cache="kv", nbytes=10)
        assert not store.insert([1, 2, 3, 4], cache="kv2", nbytes=10)
        assert store.stats()["insertions"] == 1

    def test_eviction_repairs_contended_boundary(self):
        """When the entry that WON a shared boundary is evicted, the
        index must fall back to a surviving entry covering the same
        prefix — otherwise a live prefix silently stops hitting."""
        store = PrefixStore(budget_bytes=250, align=4)
        store.insert([1, 2, 3, 4, 5, 6, 7, 8], cache="kv-a", nbytes=100)
        # B shares A's first boundary and wins the index slot for it.
        store.insert([1, 2, 3, 4, 9, 9, 9, 9], cache="kv-b", nbytes=100)
        store.lookup([1, 2, 3, 4, 5, 6, 7, 8, 0]).release()  # A now MRU
        store.insert([7, 7, 7, 7], cache="kv-c", nbytes=100)  # evicts B
        assert not store.has([1, 2, 3, 4, 9, 9, 9, 9])
        hit = store.lookup([1, 2, 3, 4, 0])
        assert hit is not None and hit.length == 4  # repaired onto A
        assert hit.entry.cache == "kv-a"
        hit.release()

    def test_digest_collision_reads_as_miss(self):
        """A forged index entry whose tokens don't match must MISS (the
        token re-verification is the collision guard)."""
        store = PrefixStore(budget_bytes=1000, align=4)
        store.insert([1, 2, 3, 4], cache="kv", nbytes=10)
        key, ref = next(iter(store._index.items()))
        entry = store._entries[ref[0]]
        entry.tokens = (9, 9, 9, 9)  # simulate colliding digest
        assert store.lookup([1, 2, 3, 4, 5]) is None


def run_scheduler_requests(engine, requests):
    sched = Scheduler(engine, debug_invariants=True)
    results = {i: [] for i in range(len(requests))}
    done = {i: threading.Event() for i in range(len(requests))}
    for i, (ids, sampling, max_new) in enumerate(requests):
        def emit(ev, i=i):
            results[i].append(ev)
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(prompt_ids=ids, sampling=sampling,
                                max_new_tokens=max_new, emit=emit,
                                id=f"r{i}"))
    sched.start()
    for ev in done.values():
        assert ev.wait(120), "request did not complete"
    sched.stop()
    return sched, results


class TestSchedulerIntegration:
    def test_hit_miss_partition_streams_match_reference(self, setup):
        """A mixed burst (one novel prompt + several sharing a cached
        prefix) partitions into miss and hit dispatch units and every
        stream equals the sequential reference."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())
        engine.release_slot(0)

        prompts = [list(b"a fresh novel one"),
                   BASE[:16] + list(b"Q1"),
                   BASE[:16] + list(b"Q2"),
                   BASE[:16] + list(b"Q3")]
        sched, results = run_scheduler_requests(
            engine, [(p, SamplingParams(), 5) for p in prompts])
        for i, p in enumerate(prompts):
            want = ByteTokenizer().decode(
                reference_greedy(cfg, params, p, 5))
            got = "".join(ev.text for ev in results[i])
            assert got.rstrip("�") == want.rstrip("�"), i
        st = engine.prefix_store.stats()
        assert st["hits"] >= 3

    def test_counters_flow_through_scheduler_stats(self, setup):
        cfg, params = setup
        # One slot: the second request admits only after the first
        # completed (and populated the store), so it must HIT.
        engine = make_engine(cfg, params, slots=1)
        sched, _ = run_scheduler_requests(
            engine, [(BASE, SamplingParams(), 3),
                     (BASE, SamplingParams(), 3)])
        stats = sched.stats()
        assert "prefix_cache" in stats
        pc = stats["prefix_cache"]
        for key in ("hits", "misses", "evictions", "bytes",
                    "budget_bytes", "hit_rate"):
            assert key in pc, key
        assert pc["hits"] >= 1
        # New admission-backlog gauges ride the same stats snapshot.
        assert stats["deferred_depth"] == 0
        assert stats["prefill_jobs_active"] == 0

    def test_disabled_cache_reports_nothing(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, cache_mb=0)
        sched, _ = run_scheduler_requests(
            engine, [(BASE, SamplingParams(), 3)])
        assert "prefix_cache" not in sched.stats()

    def test_stage_stamps_on_first_event(self, setup):
        """The first event of each request carries the recv/picked/first
        stage stamps (the TTFT attribution chain's scheduler leg)."""
        cfg, params = setup
        engine = make_engine(cfg, params, cache_mb=0)
        _sched, results = run_scheduler_requests(
            engine, [(BASE, SamplingParams(), 4)])
        staged = [ev for ev in results[0] if ev.stages]
        assert len(staged) == 1
        stages = staged[0].stages
        assert stages["recv"] <= stages["picked"] <= stages["first"]
