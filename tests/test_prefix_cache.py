"""Radix-tree prefix cache over a paged KV block pool.

Covers the acceptance surface of the radix/paged-KV PR:

  - exact hit: a repeated prompt pays ZERO full-prefill dispatches — one
    block gather + one suffix dispatch covers admission
  - block-granular matching: an UNALIGNED mid-bucket shared prefix
    (any whole-block length) hits — impossible in the old aligned store
  - decode equivalence: greedy AND seeded-sampled tokens are identical
    with the cache on vs off (the cache must be invisible to outputs)
  - zero steady-state recompiles under mixed hit/miss traffic with
    unaligned history lengths (engine.compile_cache_sizes() pinned)
  - BlockPool/RadixIndex semantics: refcounted free list, pinning,
    leaf-LRU eviction that frees blocks, two-phase insert — including a
    randomized model-based test against a plain-dict reference
  - scheduler integration: hit/miss requests partition into separate
    dispatch units inside _place_group and streams match the sequential
    reference; counters flow through scheduler.stats()
"""

import random
import threading

import jax
import jax.numpy as jnp
import pytest

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.prefix_cache import BlockPool, RadixIndex
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import forward, init_cache, init_params, preset


@pytest.fixture(scope="module")
def setup():
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    return cfg, params


def make_engine(cfg, params, slots=4, cache_mb=16, chunk=8,
                buckets=(16, 32), block=8):
    return InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=slots, max_seq_len=64,
        prefill_buckets=buckets, cache_dtype=jnp.float32,
        prefill_chunk=chunk, prefix_cache_bytes=cache_mb * 2**20,
        prefix_block_tokens=block)


def reference_greedy(cfg, params, prompt_ids, n_tokens):
    cache = init_cache(cfg, 1, 64, jnp.float32)
    tokens = jnp.asarray([prompt_ids], jnp.int32)
    logits, cache = forward(params, cfg, tokens, cache)
    out = []
    last = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    out.append(int(last[0]))
    for _ in range(n_tokens - 1):
        logits, cache = forward(params, cfg, last[:, None], cache)
        last = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
        out.append(int(last[0]))
    return out


def count_dispatches(engine):
    """Wrap the full-prefill and suffix jits with call counters."""
    counts = {"prefill": 0, "chunk_final": 0, "chunk_step": 0}
    real_prefill, real_final = engine._prefill, engine._chunk_final
    real_step = engine._chunk_step

    def prefill(*a, **kw):
        counts["prefill"] += 1
        return real_prefill(*a, **kw)

    def final(*a, **kw):
        counts["chunk_final"] += 1
        return real_final(*a, **kw)

    def step(*a, **kw):
        counts["chunk_step"] += 1
        return real_step(*a, **kw)

    engine._prefill = prefill
    engine._chunk_final = final
    engine._chunk_step = step
    return counts


BASE = list(b"hello world prefix!")  # 19 tokens -> 2 whole blocks @ 8


class TestEngineHitPaths:
    def test_exact_hit_skips_full_prefill(self, setup):
        """Second identical prompt: zero full-prefill dispatches — one
        block gather + one suffix dispatch covers admission."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        want = reference_greedy(cfg, params, BASE, 6)

        first = engine.prefill_and_insert(0, BASE, SamplingParams())
        got_miss = [first] + [int(engine.decode_step()[0])
                              for _ in range(5)]
        assert got_miss == want
        # (hit/miss counters tick per ADMITTED request — the direct
        # engine call here only stores.)
        st = engine.prefix_index.stats()
        assert st["insertions"] == 1 and st["blocks_in_use"] == 2

        counts = count_dispatches(engine)
        hit = engine.prefix_lookup(BASE)
        assert hit is not None and hit.length == 16
        assert len(hit.blocks) == 2
        firsts = engine.prefill_and_insert_cached(
            [(1, BASE, SamplingParams())], hit)
        assert counts["prefill"] == 0  # cached portion: no prefill
        assert counts["chunk_final"] == 1  # suffix-only dispatch
        got_hit = list(firsts) + [int(engine.decode_step()[1])
                                  for _ in range(5)]
        assert got_hit == want
        st = engine.prefix_index.stats()
        assert st["hits"] == 1 and st["tokens_reused"] == 16

    def test_unaligned_mid_bucket_prefix_hits(self, setup):
        """THE new capability: a shared prefix of arbitrary (non-bucket,
        non-chunk-aligned) length hits at block granularity. 13 shared
        tokens match at 8 (one whole block) — the old aligned store
        could only match multiples of prefix_align AND only at lengths
        some entry was stored at."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())

        other = BASE[:13] + list(b"XYZ")  # 16 tokens, shares 13
        want = reference_greedy(cfg, params, other, 4)
        counts = count_dispatches(engine)
        hit = engine.prefix_lookup(other)
        assert hit is not None and hit.length == 8
        assert hit.tokens == tuple(other[:8])
        firsts = engine.prefill_and_insert_cached(
            [(1, other, SamplingParams())], hit)
        assert counts["prefill"] == 0 and counts["chunk_final"] == 1
        got = list(firsts) + [int(engine.decode_step()[1])
                              for _ in range(3)]
        assert got == want
        # The cached admission EXTENDED the tree with `other`'s own
        # whole-block prefix — the multi-turn session-cache mechanism.
        assert engine.prefix_index.covers(other[:16])

    def test_partial_hit_suffix_only(self, setup):
        """A prompt sharing whole blocks prefills only its own suffix
        and still matches the sequential reference."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())

        other = BASE[:16] + list(b"XYZ")
        want = reference_greedy(cfg, params, other, 6)
        counts = count_dispatches(engine)
        hit = engine.prefix_lookup(other)
        assert hit is not None and hit.length == 16
        firsts = engine.prefill_and_insert_cached(
            [(1, other, SamplingParams())], hit)
        assert counts["prefill"] == 0 and counts["chunk_final"] == 1
        got = list(firsts) + [int(engine.decode_step()[1])
                              for _ in range(5)]
        assert got == want

    def test_sub_prefix_of_longer_entry_hits(self, setup):
        """KV is causal: the first block of a 2-block entry IS the
        8-token prefix's KV, so a prompt sharing only 8 tokens still
        hits at 8 — and the radix tree serves it from the SAME blocks."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())

        other = BASE[:8] + list(b"tail998")
        want = reference_greedy(cfg, params, other, 4)
        hit = engine.prefix_lookup(other)
        assert hit is not None and hit.length == 8
        firsts = engine.prefill_and_insert_cached(
            [(1, other, SamplingParams())], hit)
        got = list(firsts) + [int(engine.decode_step()[1])
                              for _ in range(3)]
        assert got == want

    def test_long_suffix_runs_seeded_chunked(self, setup):
        """Suffix beyond one alignment unit: the hit seeds a chunked
        prefill instead (blocks gathered, chunks cover only the
        suffix), and the finished buffer's NEW blocks extend the tree."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())

        prompt = BASE[:8] + list(b"different tail..")  # 24 tok, sfx 16
        want = reference_greedy(cfg, params, prompt, 4)
        hit = engine.prefix_lookup(prompt)
        assert hit is not None and hit.length == 8
        assert engine.seeded_chunk_ok(len(prompt))
        counts = count_dispatches(engine)
        job = engine.start_chunked_prefill(1, prompt, SamplingParams(),
                                           hit=hit)
        assert job.start_pos == 8 and job.suffix_len == 16
        first = None
        while first is None:
            first = engine.advance_chunked_prefill(job)
        assert counts["prefill"] == 0
        got = [first] + [int(engine.decode_step()[1]) for _ in range(3)]
        assert got == want
        # tail adoption: the completed 24-token prefix is covered, and
        # the shared first block was NOT duplicated (3 new blocks only)
        assert engine.prefix_index.covers(prompt[:24])
        st = engine.prefix_index.stats()
        assert st["blocks_in_use"] == 4  # 2 (BASE) + 2 (new tail)

    def test_coalesced_hit_group_with_pad_rows(self, setup):
        """Several requests sharing one (node, matched_len) admit as ONE
        cached unit (batch padded to the compiled width) and each stream
        matches its own sequential reference."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())
        prompts = [BASE[:16] + list(b"A%d" % i) for i in range(3)]
        wants = [reference_greedy(cfg, params, p, 3) for p in prompts]

        hit = engine.prefix_lookup(prompts[0])
        firsts = engine.prefill_and_insert_cached(
            [(i, p, SamplingParams()) for i, p in enumerate(prompts)], hit)
        gots = [[f] for f in firsts]
        for _ in range(2):
            toks = engine.decode_step()
            for i in range(3):
                gots[i].append(int(toks[i]))
        assert gots == wants

    def test_seeded_sampling_identical_cache_on_off(self, setup):
        """A seeded sampled request reproduces its EXACT completion
        whether admission went through the cache or a full prefill."""
        cfg, params = setup
        sp = SamplingParams(temperature=0.9, top_p=0.95, seed=42)

        engine_off = make_engine(cfg, params, cache_mb=0)
        assert engine_off.prefix_index is None
        toks_off = [engine_off.prefill_and_insert(0, BASE, sp)]
        toks_off += [int(engine_off.decode_step()[0]) for _ in range(5)]

        engine_on = make_engine(cfg, params)
        engine_on.prefill_and_insert(0, BASE, SamplingParams(seed=7))
        hit = engine_on.prefix_lookup(BASE)
        assert hit is not None
        toks_on = list(engine_on.prefill_and_insert_cached(
            [(1, BASE, sp)], hit))
        toks_on += [int(engine_on.decode_step()[1]) for _ in range(5)]
        assert toks_on == toks_off

    def test_warmup_then_hit_path(self, setup):
        """warmup() with the cache enabled (extra compile grid) must not
        perturb subsequent cached admissions."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.warmup()
        want = reference_greedy(cfg, params, BASE, 4)
        engine.prefill_and_insert(0, BASE, SamplingParams())
        hit = engine.prefix_lookup(BASE)
        firsts = engine.prefill_and_insert_cached(
            [(1, BASE, SamplingParams())], hit)
        got = list(firsts) + [int(engine.decode_step()[1])
                              for _ in range(3)]
        assert got == want

    def test_zero_steady_state_recompiles_unaligned_traffic(self, setup):
        """After warmup, mixed hit/miss traffic with UNALIGNED history
        lengths must not grow any jit cache — block-granular matching
        moves lengths into data (ids vectors, traced scalars), never
        into shapes."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.warmup()
        baseline = engine.compile_cache_sizes()
        assert baseline["_insert_from_blocks"] > 0
        assert baseline["_write_blocks"] > 0
        # Prime the cache so the burst's shared-prefix members hit
        # deterministically (a cold burst looks everything up before
        # anything stores).
        engine.prefill_and_insert(0, BASE, SamplingParams())
        engine.release_slot(0)
        prompts = [BASE,                      # exact hit
                   BASE[:13] + list(b"XY"),   # unaligned 13-shared hit
                   BASE[:11] + list(b"qrs"),  # unaligned 11-shared hit
                   list(b"totally new one!"),  # miss
                   BASE[:8] + list(b"different tail..")]  # seeded chunk
        sched, results = run_scheduler_requests(
            engine, [(p, SamplingParams(), 3) for p in prompts])
        for evs in results.values():
            assert evs and evs[-1].done
            assert evs[-1].finish_reason in ("stop", "length")
        assert engine.compile_cache_sizes() == baseline, \
            "steady-state traffic recompiled a serving program"
        assert engine.prefix_index.stats()["hits"] >= 2


# ---------------------------------------------------------------------
# BlockPool / RadixIndex semantics (no engine, no device)


def mk_index(n_blocks=16, bs=4):
    return RadixIndex(BlockPool(n_blocks, bs, block_bytes=100))


def do_insert(idx, tokens):
    plan = idx.plan_insert(tokens)
    if plan is not None:
        plan.commit()
    return plan


class TestRadixSemantics:
    def test_two_phase_insert_and_reuse(self):
        idx = mk_index()
        plan = idx.plan_insert(list(range(8)))
        assert plan.matched_len == 0 and len(plan.new_ids) == 2
        plan.commit()
        # extension allocates only the tail
        plan2 = idx.plan_insert(list(range(12)))
        assert plan2.matched_len == 8 and len(plan2.new_ids) == 1
        plan2.commit()
        assert idx.pool.in_use == 3
        # fully resident -> no plan
        assert idx.plan_insert(list(range(12))) is None

    def test_abort_returns_blocks(self):
        idx = mk_index(n_blocks=4)
        plan = idx.plan_insert(list(range(16)))
        assert plan is not None and idx.pool.free_count == 0
        plan.abort()
        assert idx.pool.free_count == 4 and idx.pool.in_use == 0
        assert idx.match_len(list(range(16))) == 0

    def test_lookup_strictly_partial_and_pinned(self):
        idx = mk_index()
        do_insert(idx, list(range(8)))
        hit = idx.lookup(list(range(8)))
        # suffix must keep >= 1 token: an exact-length prompt matches
        # only its first block
        assert hit.length == 4
        assert idx.pool.refcount(hit.blocks[0]) == 2
        assert idx.pool.pinned == 1
        hit.release()
        hit.release()  # idempotent
        assert idx.pool.pinned == 0

    def test_pinned_blocks_survive_eviction_pressure(self):
        idx = mk_index(n_blocks=3)
        do_insert(idx, [1, 2, 3, 4])
        hit = idx.lookup([1, 2, 3, 4, 9])
        assert hit is not None
        # needs 3 blocks, pool has 2 free + 1 pinned: insert must be
        # REJECTED, not evict the pinned block
        assert idx.plan_insert([5, 6, 7, 8, 9, 10, 11, 12,
                                13, 14, 15, 16]) is None
        st = idx.stats()
        assert st["rejected"] == 1 and st["evictions"] == 0
        assert idx.match_len([1, 2, 3, 4]) == 4
        hit.release()
        # released: leaf-LRU eviction frees the block for the retry
        plan = idx.plan_insert([5, 6, 7, 8, 9, 10, 11, 12,
                                13, 14, 15, 16])
        assert plan is not None
        plan.commit()
        assert idx.match_len([1, 2, 3, 4]) == 0  # evicted
        assert idx.stats()["evictions"] == 1

    def test_plan_pins_its_own_matched_prefix(self):
        """Regression: extending a resident prefix under pool pressure
        must never evict the matched prefix itself (the plan pins it) —
        the insert is rejected instead, and an unrelated cold leaf is
        still fair game."""
        idx = mk_index(n_blocks=2)
        do_insert(idx, [1, 2, 3, 4])
        # needs 2 new blocks, 1 free, and the only evictable leaf is
        # the matched prefix: must reject, not crash in commit
        assert idx.plan_insert(list(range(1, 13))) is None
        assert idx.match_len([1, 2, 3, 4]) == 4
        assert idx.stats()["rejected"] == 1
        assert idx.pool.pinned == 0  # plan released its pin on failure
        # an unrelated cold leaf still evicts to make room
        idx2 = mk_index(n_blocks=3)
        do_insert(idx2, [9, 9, 9, 9])
        do_insert(idx2, [1, 2, 3, 4])
        plan = idx2.plan_insert([1, 2, 3, 4, 5, 6, 7, 8, 1, 1, 1, 1])
        assert plan is not None
        plan.commit()
        assert idx2.match_len([9, 9, 9, 9]) == 0
        assert idx2.match_len([1, 2, 3, 4, 5, 6, 7, 8, 1, 1, 1, 1]) == 12
        assert idx2.pool.pinned == 0

    def test_leaf_lru_eviction_order(self):
        """The least-recently-touched LEAF goes first; interior nodes
        only become evictable once their children are gone."""
        idx = mk_index(n_blocks=4)
        do_insert(idx, [1, 2, 3, 4])              # parent-to-be
        do_insert(idx, [1, 2, 3, 4, 5, 6, 7, 8])  # child A (leaf)
        do_insert(idx, [1, 2, 3, 4, 9, 9, 9, 9])  # child B (leaf)
        assert idx.pool.free_count == 1
        idx.lookup([1, 2, 3, 4, 5, 6, 7, 8, 0]).release()  # A is MRU
        plan = idx.plan_insert([7, 7, 7, 7, 8, 8, 8, 8])  # needs 2
        assert plan is not None
        plan.commit()
        # B (LRU leaf) was evicted; A and the shared parent survive
        assert idx.match_len([1, 2, 3, 4, 9, 9, 9, 9]) == 4
        assert idx.match_len([1, 2, 3, 4, 5, 6, 7, 8]) == 8

    def test_divergent_insert_splits_edge(self):
        """Inserting a sequence that diverges INSIDE an existing edge
        splits at the block boundary; both descendants keep hitting."""
        idx = mk_index()
        do_insert(idx, list(range(12)))           # one 3-block edge
        do_insert(idx, list(range(8)) + [77, 77, 77, 77])
        assert idx.pool.in_use == 4  # 3 + 1 new (2 shared by reference)
        assert idx.match_len(list(range(12))) == 12
        assert idx.match_len(list(range(8)) + [77, 77, 77, 77]) == 12
        h = idx.lookup(list(range(12)) + [0])
        h2 = idx.lookup(list(range(8)) + [77, 77, 77, 77, 0])
        assert h.blocks[:2] == h2.blocks[:2]  # shared by reference
        assert h.blocks[2] != h2.blocks[2]
        h.release()
        h2.release()

    def test_partial_tail_never_stored(self):
        """plan_insert refuses non-whole-block lengths (callers floor
        to whole blocks); a partial tail never becomes a tree node."""
        idx = mk_index()
        assert idx.plan_insert([1, 2, 3]) is None       # < one block
        assert idx.plan_insert([1, 2, 3, 4, 5]) is None  # ragged tail
        assert idx.pool.in_use == 0
        assert idx.match_len([1, 2, 3, 4, 5]) == 0

    def test_hbm_high_water_tracks_peak(self):
        idx = mk_index(n_blocks=4)
        do_insert(idx, [1, 2, 3, 4, 5, 6, 7, 8])
        assert idx.stats()["hbm_high_water_bytes"] == 200
        # eviction lowers in_use but never the high-water mark
        p = idx.plan_insert([9, 9, 9, 9, 8, 8, 8, 8, 7, 7, 7, 7])
        p.commit()
        st = idx.stats()
        assert st["blocks_in_use"] == 3
        assert st["hbm_high_water_bytes"] == 300

    def test_randomized_model_based(self):
        """A few hundred scripted insert/lookup/evict/refcount ops
        checked against a plain-dict reference model. Phase 1 (no
        eviction pressure): the reference predicts every match length
        exactly. Phase 2 (tight pool): structural invariants — block
        conservation, refcount exactness, pins never freed, matched
        tokens always a true prefix."""
        rng = random.Random(1234)
        bs = 4

        # ---- phase 1: big pool, exact-match reference
        idx = mk_index(n_blocks=512, bs=bs)
        covered: set[tuple] = set()  # every committed block's context

        def ref_match(seq):
            n = 0
            while (n + 1) * bs <= len(seq) and \
                    tuple(seq[:(n + 1) * bs]) in covered:
                n += 1
            return n * bs

        pool_seqs = [[rng.randrange(5) for _ in range(rng.randrange(
            bs, 8 * bs))] for _ in range(40)]
        for _ in range(300):
            seq = rng.choice(pool_seqs)
            op = rng.random()
            if op < 0.5:
                p = bs * (len(seq) // bs)
                plan = idx.plan_insert(seq[:p])
                want_new = (p - ref_match(seq[:p])) // bs
                if want_new == 0 or p == 0:
                    assert plan is None
                else:
                    assert plan is not None
                    assert len(plan.new_ids) == want_new
                    plan.commit()
                    for j in range(p // bs):
                        covered.add(tuple(seq[:(j + 1) * bs]))
            else:
                m = ref_match(seq)
                assert idx.match_len(seq) == m
                hit = idx.lookup(seq)
                want = min(m, bs * ((len(seq) - 1) // bs))
                if want == 0:
                    assert hit is None
                else:
                    assert hit is not None and hit.length == want
                    assert hit.tokens == tuple(seq[:want])
                    hit.release()
        assert idx.pool.in_use == len(covered)
        assert idx.pool.in_use + idx.pool.free_count == 512

        # ---- phase 2: tight pool, invariants under churn
        idx = mk_index(n_blocks=8, bs=bs)
        held = []
        for _ in range(300):
            seq = [rng.randrange(4) for _ in range(rng.randrange(
                bs, 6 * bs))]
            op = rng.random()
            if op < 0.45:
                p = bs * (len(seq) // bs)
                plan = idx.plan_insert(seq[:p])
                if plan is not None:
                    if rng.random() < 0.1:
                        plan.abort()
                    else:
                        plan.commit()
            elif op < 0.8:
                hit = idx.lookup(seq)
                if hit is not None:
                    assert hit.length % bs == 0
                    assert hit.length < len(seq)
                    assert hit.tokens == tuple(seq[:hit.length])
                    if rng.random() < 0.3 and len(held) < 3:
                        held.append(hit)
                    else:
                        hit.release()
            elif held:
                held.pop(rng.randrange(len(held))).release()
            # invariants, every op
            pool = idx.pool
            assert pool.in_use + pool.free_count == pool.n_blocks
            assert pool.in_use * pool.block_bytes == idx.bytes_used
            for h in held:
                for b in h.blocks:
                    assert pool.refcount(b) >= 2  # pinned, never freed
            st = idx.stats()
            assert st["blocks_in_use"] == pool.in_use
        for h in held:
            h.release()
        assert idx.pool.pinned == 0


# ---------------------------------------------------------------------
# Scheduler integration


def run_scheduler_requests(engine, requests):
    sched = Scheduler(engine, debug_invariants=True)
    results = {i: [] for i in range(len(requests))}
    done = {i: threading.Event() for i in range(len(requests))}
    for i, (ids, sampling, max_new) in enumerate(requests):
        def emit(ev, i=i):
            results[i].append(ev)
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(prompt_ids=ids, sampling=sampling,
                                max_new_tokens=max_new, emit=emit,
                                id=f"r{i}"))
    sched.start()
    for ev in done.values():
        assert ev.wait(120), "request did not complete"
    sched.stop()
    return sched, results


class TestSchedulerIntegration:
    def test_hit_miss_partition_streams_match_reference(self, setup):
        """A mixed burst (one novel prompt + several sharing cached
        blocks, INCLUDING an unaligned-history one) partitions into miss
        and hit dispatch units and every stream equals the sequential
        reference."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.prefill_and_insert(0, BASE, SamplingParams())
        engine.release_slot(0)

        prompts = [list(b"a fresh novel one"),
                   BASE[:16] + list(b"Q1"),
                   BASE[:16] + list(b"Q2"),
                   BASE[:13] + list(b"Q3")]  # unaligned 13-token share
        sched, results = run_scheduler_requests(
            engine, [(p, SamplingParams(), 5) for p in prompts])
        for i, p in enumerate(prompts):
            want = ByteTokenizer().decode(
                reference_greedy(cfg, params, p, 5))
            got = "".join(ev.text for ev in results[i])
            assert got.rstrip("�") == want.rstrip("�"), i
        st = engine.prefix_index.stats()
        assert st["hits"] >= 3

    def test_counters_flow_through_scheduler_stats(self, setup):
        cfg, params = setup
        # One slot: the second request admits only after the first
        # completed (and populated the pool), so it must HIT.
        engine = make_engine(cfg, params, slots=1)
        sched, _ = run_scheduler_requests(
            engine, [(BASE, SamplingParams(), 3),
                     (BASE, SamplingParams(), 3)])
        stats = sched.stats()
        assert "prefix_cache" in stats
        pc = stats["prefix_cache"]
        for key in ("hits", "misses", "evictions", "bytes",
                    "budget_bytes", "hit_rate", "blocks_in_use",
                    "blocks_total", "block_tokens",
                    "hbm_high_water_bytes"):
            assert key in pc, key
        assert pc["hits"] >= 1
        # New admission-backlog gauges ride the same stats snapshot.
        assert stats["deferred_depth"] == 0
        assert stats["prefill_jobs_active"] == 0

    def test_disabled_cache_reports_nothing(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, cache_mb=0)
        sched, _ = run_scheduler_requests(
            engine, [(BASE, SamplingParams(), 3)])
        assert "prefix_cache" not in sched.stats()

    def test_stage_stamps_on_first_event(self, setup):
        """The first event of each request carries the recv/picked/first
        stage stamps (the TTFT attribution chain's scheduler leg)."""
        cfg, params = setup
        engine = make_engine(cfg, params, cache_mb=0)
        _sched, results = run_scheduler_requests(
            engine, [(BASE, SamplingParams(), 4)])
        staged = [ev for ev in results[0] if ev.stages]
        assert len(staged) == 1
        stages = staged[0].stages
        assert stages["recv"] <= stages["picked"] <= stages["first"]


class TestLifecycleLeakRegressions:
    """Regressions for the real L4xx findings symlint's lifecycle
    checker surfaced (the PR-12 crash class, path-sensitively): every
    failure between a plan's acquisition and its commit must abort the
    plan, or the matched-prefix pins and freshly allocated blocks leak
    until restart."""

    def test_plan_insert_eviction_failure_releases_everything(self):
        idx = mk_index(n_blocks=4)
        do_insert(idx, [1, 2, 3, 4, 5, 6, 7, 8])
        do_insert(idx, [9, 10, 11, 12, 13, 14, 15, 16])
        assert idx.pool.free_count == 0

        def boom():
            raise RuntimeError("eviction exploded")

        idx._evict_one = boom
        with pytest.raises(RuntimeError, match="eviction exploded"):
            # shares the first 2 blocks (pinned by the plan), needs a
            # third → alloc fails → eviction raises mid-plan
            idx.plan_insert([1, 2, 3, 4, 5, 6, 7, 8,
                             91, 92, 93, 94])
        del idx._evict_one
        # the matched-prefix pins were released and nothing leaked:
        # tree ownership is the only reference again
        assert idx.pool.pinned == 0
        assert idx.pool.in_use == 4 and idx.pool.free_count == 0
        # the index is still healthy — the same insert succeeds once
        # eviction works again (evicts the other entry's leaf)
        plan = idx.plan_insert([1, 2, 3, 4, 5, 6, 7, 8, 91, 92, 93, 94])
        assert plan is not None and plan.matched_len == 8
        plan.commit()
        assert idx.match_len([1, 2, 3, 4, 5, 6, 7, 8, 91, 92, 93, 94,
                              0]) == 12

    def test_store_prefix_extract_failure_aborts_plan(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params)

        def boom(*a, **kw):
            raise RuntimeError("device error in extract")

        engine._extract_prefix_row = boom
        with pytest.raises(RuntimeError, match="device error"):
            engine._maybe_store_prefix(
                [(0, list(range(16)), SamplingParams())], None)
        pool = engine.prefix_index.pool
        # plan aborted: no pins held, every allocated block returned
        assert pool.pinned == 0 and pool.in_use == 0


class TestGossipSummary:
    """Engine-level contract for the pool-gossip rider cadence."""

    def test_gossip_s_zero_means_always_fresh(self, setup):
        cfg, params = setup
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=64,
            prefill_buckets=(16, 32), cache_dtype=jnp.float32,
            prefill_chunk=8, prefix_cache_bytes=16 * 2**20,
            prefix_block_tokens=8, prefix_gossip_blocks=8,
            prefix_gossip_s=0.0)
        # empty tree gossips nothing — and an explicit 0.0 cadence must
        # not CACHE that None (a heartbeat probe right after the first
        # insertion has to see the summary, not a stale empty walk)
        assert engine.prefix_cache_summary() is None
        plan = engine.prefix_index.plan_insert(list(range(16)))
        assert plan is not None
        plan.commit()
        s = engine.prefix_cache_summary()
        assert s is not None and s["block_tokens"] == 8
        assert len(s["digests"]) == 2

    def test_gossip_s_caches_the_walk(self, setup):
        cfg, params = setup
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=64,
            prefill_buckets=(16, 32), cache_dtype=jnp.float32,
            prefill_chunk=8, prefix_cache_bytes=16 * 2**20,
            prefix_block_tokens=8, prefix_gossip_blocks=8,
            prefix_gossip_s=60.0)
        assert engine.prefix_cache_summary() is None
        plan = engine.prefix_index.plan_insert(list(range(16)))
        plan.commit()
        # within the cadence window the cached (empty) walk is reused
        assert engine.prefix_cache_summary() is None

    def test_gossip_blocks_zero_disables_rider(self, setup):
        cfg, params = setup
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=64,
            prefill_buckets=(16, 32), cache_dtype=jnp.float32,
            prefill_chunk=8, prefix_cache_bytes=16 * 2**20,
            prefix_block_tokens=8, prefix_gossip_blocks=0)
        plan = engine.prefix_index.plan_insert(list(range(16)))
        plan.commit()
        # a populated tree still gossips nothing when the rider is off
        assert engine.prefix_cache_summary() is None
