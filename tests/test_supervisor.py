"""Engine-host supervision chaos suite.

Proves the recovery machinery with REAL injected faults at the real
seams (utils/faults.py), no TPU and no network:

  - host crash mid-stream (SYMMETRY_FAULTS-style `host.pipe_write=crash`)
    → in-flight streams get the retryable restarting shed, the
    supervisor respawns the host, and the next request serves normally;
  - host wedge (`host.pipe_read=hang`) → the watchdog's stats-probe
    deadline detects it far inside the 15 s health-loop window, kills
    the process, and the same restart path runs;
  - persistently failing respawns → the circuit breaker opens after
    max_respawns consecutive failures and healthy() goes false (the
    pre-supervisor deregistration path);
  - scheduler admission seams: injected admit errors fail exactly one
    request, injected drops lose it silently (what the watchdog exists
    to catch), and expired deadlines are shed at admission without a
    prefill dispatch.

The host subprocess is tests/fake_host.py — protocol-faithful, JAX-free,
instrumented with the same FAULTS seams as engine/host.py — so a
crash/respawn life costs milliseconds instead of an engine build.
Scheduler-level tests use the real tiny JAX engine.
"""

import asyncio
import os
import sys
import threading
import time

import pytest

from symmetry_tpu.provider.backends.base import (
    BackendDeadlineError,
    BackendError,
    BackendRestartingError,
    InferenceRequest,
)
from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.utils.faults import FAULTS, InjectedFault

FAKE_HOST = os.path.join(os.path.dirname(__file__), "fake_host.py")


@pytest.fixture(autouse=True)
def clean_global_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def run(coro):
    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(coro, 60))


class FakeHostBackend(TpuNativeBackend):
    """tpu_native process mode against the protocol-faithful fake host."""

    def _host_argv(self, cfg_path):
        return [sys.executable, FAKE_HOST, cfg_path]


def fake_cfg(faults=None, sup=None, fake_host=None):
    supervisor = {"heartbeat_s": 30.0, "wedge_timeout_s": 1.0,
                  "backoff_base_s": 0.05, "backoff_max_s": 0.2,
                  "max_respawns": 2, "spawn_timeout_s": 15.0,
                  "stop_grace_s": 0.5, **(sup or {})}
    return ConfigManager(config={
        "name": "chaos-prov", "public": False, "serverKey": "00" * 32,
        "modelName": "fake:chaos", "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "tpu": {"engine_isolation": "process", "max_batch_size": 4,
                "supervisor": supervisor},
        **({"faults": faults} if faults else {}),
        **({"fakeHost": fake_host} if fake_host else {}),
    })


async def collect_stream(backend, max_tokens, content="chaos"):
    text = []
    async for chunk in backend.stream(InferenceRequest(
            messages=[{"role": "user", "content": content}],
            max_tokens=max_tokens)):
        if chunk.text:
            text.append(chunk.text)
    return "".join(text)


async def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return False


class TestSupervisor:
    def test_crash_midstream_sheds_and_respawns(self):
        """The flagship path: SYMMETRY_FAULTS-shaped crash mid-stream →
        the in-flight stream gets the structured RETRYABLE restarting
        error, the supervisor respawns the host, the next request
        completes, and engine_stats records the restart.

        Write arithmetic (fake host, per life): ready=1 + clock×5 = 6
        startup writes, so `nth=20` crashes life 1 on its 14th stream
        event (mid-stream, ~0.3 s in) while life 2 — startup + a
        3-token chat + one stats reply = 10 writes — never reaches it."""
        # The seam spec is exactly what SYMMETRY_FAULTS would carry; the
        # config mapping reaches the host subprocess via its config copy.
        cfg = fake_cfg(faults={"host.pipe_write": "crash@nth=20"})
        restarts_seen = []

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            backend.on_host_restart = restarts_seen.append
            try:
                with pytest.raises(BackendRestartingError) as exc_info:
                    await collect_stream(backend, max_tokens=40)
                # the shed carries the retry hint the provider forwards
                assert exc_info.value.retry_after_s is not None
                assert await wait_for(
                    lambda: backend._restarts >= 1
                    and not backend._restarting), "no respawn"
                assert restarts_seen == ["crash"]
                # the respawned host serves normally
                text = await collect_stream(backend, max_tokens=3)
                assert text == "t0 t1 "
                stats = await backend.engine_stats()
                assert stats["supervisor"]["restarts"] == 1
                assert stats["supervisor"]["circuit_open"] is False
                assert await backend.healthy()
            finally:
                await backend.stop()

        run(main())

    def test_new_stream_during_restart_gets_restarting_shed(self):
        """A request arriving while the host is down must be shed with
        the retryable restarting error, not hang on a dead pipe."""
        # Long backoff so the restart window is reliably open when the
        # second stream arrives.
        cfg = fake_cfg(faults={"host.pipe_write": "crash@nth=8"},
                       sup={"backoff_base_s": 1.0, "backoff_max_s": 1.0})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                with pytest.raises(BackendRestartingError):
                    await collect_stream(backend, max_tokens=40)
                # inside the backoff window: host is down, not yet back
                with pytest.raises(BackendRestartingError):
                    await collect_stream(backend, max_tokens=2)
                # supervised death is a transient, not a health failure
                assert await backend.healthy()
            finally:
                await backend.stop()

        run(main())

    def test_wedge_detected_by_watchdog_and_restarted(self):
        """A host that is alive but not answering (hung read loop) must
        be detected by the stats-probe watchdog within its own deadline
        — far tighter than the 15 s health loop — then killed and
        respawned, failing the wedged in-flight stream as restarting.

        Read arithmetic (fake host, per life): clock×5 = reads 1–5, so
        `nth=6` hangs the FIRST post-handshake command — the submit (or
        the first watchdog probe, whichever lands first); either way the
        stream stalls and only the watchdog can notice."""
        cfg = fake_cfg(faults={"host.pipe_read": "hang(120)@nth=6"},
                       sup={"heartbeat_s": 0.15, "wedge_timeout_s": 0.4})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            t0 = time.monotonic()
            try:
                with pytest.raises(BackendRestartingError):
                    await collect_stream(backend, max_tokens=40)
                # detection + shed must beat the 15 s health-loop floor
                assert time.monotonic() - t0 < 10.0
                assert await wait_for(lambda: backend._restarts >= 1)
            finally:
                await backend.stop()

        run(main())

    def test_circuit_breaker_opens_after_consecutive_respawn_failures(
            self, tmp_path):
        """Respawns that keep dying (failFile arms the fake host to exit
        before ready) must trip the breaker after max_respawns=2
        consecutive failures: healthy() false (→ the provider health
        loop deregisters), new streams get a terminal error, and the
        supervisor stops burning respawns."""
        fail_file = tmp_path / "respawn.fail"
        cfg = fake_cfg(fake_host={"failFile": str(fail_file)})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                assert await backend.healthy()
                fail_file.write_text("die")       # every next life fails
                backend._proc.kill()              # the initial crash
                assert await wait_for(lambda: backend._circuit_open), \
                    "circuit breaker never opened"
                assert backend._respawn_failures == 2
                assert not await backend.healthy()
                # circuit-open is terminal, not retryable
                with pytest.raises(BackendError) as exc_info:
                    await collect_stream(backend, max_tokens=2)
                assert not isinstance(exc_info.value,
                                      BackendRestartingError)
                stats = await backend.engine_stats()
                assert stats["supervisor"]["circuit_open"] is True
            finally:
                await backend.stop()

        run(main())

    def test_reader_death_without_eof_is_recovered_by_heartbeat(self):
        """If the reader task dies WITHOUT running its EOF path (an
        unexpected exception), nobody fails streams or wakes the
        supervisor — the heartbeat must notice the dead reader and run
        the death path itself instead of spinning forever against a
        zombie backend."""
        cfg = fake_cfg(sup={"heartbeat_s": 0.1})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                # Simulate the reader dying ungracefully: cancel it so
                # its EOF path never runs (the cancelled path skips it
                # by design).
                backend._reader.cancel()
                assert await wait_for(
                    lambda: backend._restarts >= 1
                    and not backend._restarting), \
                    "heartbeat never recovered the dead reader"
                text = await collect_stream(backend, max_tokens=3)
                assert text == "t0 t1 "
            finally:
                await backend.stop()

        run(main())

    def test_reader_eof_idempotent_after_manual_death_handling(self):
        """When the heartbeat's backstop already handled a death (set
        _host_dead, failed streams, signaled the supervisor), a LATE
        reader EOF for the same life must be a no-op — re-signaling
        _host_down would wake the supervisor a second time after the
        respawn and kill the healthy new host as a spurious stability
        failure."""
        cfg = fake_cfg()  # heartbeat 30s: the real backstop stays quiet

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                # Simulate the backstop having handled this death first.
                backend._host_dead = True
                backend._proc.kill()  # reader EOF arrives late
                await asyncio.sleep(0.5)
                assert not backend._host_down.is_set(), \
                    "late EOF re-signaled an already-handled death"
                assert backend._restarts == 0
            finally:
                await backend.stop()

        run(main())

    def test_crash_loop_trips_breaker_despite_successful_spawns(self):
        """Every spawn SUCCEEDS but every life dies young (dieAfterS):
        only a life that survives min_stable_s resets the failure count,
        so the crash-loop walks the backoff ladder into the breaker
        instead of flapping forever on reset-by-spawn-success."""
        cfg = fake_cfg(fake_host={"dieAfterS": 0.1},
                       sup={"max_respawns": 3, "min_stable_s": 5.0})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                assert await wait_for(lambda: backend._circuit_open,
                                      timeout=20), \
                    "crash loop never tripped the breaker"
                # deaths counted: initial + each short-lived respawn
                assert backend._respawn_failures == 3
                assert backend._restarts >= 1  # spawns DID succeed
                assert not await backend.healthy()
            finally:
                await backend.stop()

        run(main())

    def test_unsupervised_death_keeps_legacy_behavior(self):
        """supervisor.enabled=false restores the pre-supervisor contract:
        a dead host fails healthy() and streams get a plain terminal
        BackendError (no restarting shed, no respawn)."""
        cfg = fake_cfg(sup={"enabled": False})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                backend._proc.kill()
                assert await wait_for(lambda: backend._host_dead)
                assert not await backend.healthy()
                with pytest.raises(BackendError) as exc_info:
                    await collect_stream(backend, max_tokens=2)
                assert not isinstance(exc_info.value,
                                      BackendRestartingError)
                assert backend._restarts == 0
            finally:
                await backend.stop()

        run(main())


# --------------------------------------------------------------------------
# Scheduler-level chaos: admission seams + deadline sheds (real tiny engine)
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_engine_factory():
    import jax
    import jax.numpy as jnp

    from symmetry_tpu.engine.engine import InferenceEngine
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, preset

    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)

    def build():
        return InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                               max_seq_len=64, prefill_buckets=(16, 32),
                               cache_dtype=jnp.float32, decode_block=1)

    return build


def drive_scheduler(sched, requests, timeout=60):
    """Submit GenRequests; wait for each listed done-event (or timeout).
    Returns {idx: [events]} and a {idx: completed} map."""
    from symmetry_tpu.engine.scheduler import GenRequest
    from symmetry_tpu.engine.engine import SamplingParams

    results = {i: [] for i in range(len(requests))}
    done = {i: threading.Event() for i in range(len(requests))}
    for i, kw in enumerate(requests):
        def emit(ev, i=i):
            results[i].append(ev)
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(prompt_ids=list(b"req %d" % i),
                                sampling=SamplingParams(), emit=emit,
                                id=f"r{i}", **kw))
    completed = {i: done[i].wait(timeout) for i in range(len(requests))}
    return results, completed


class TestSchedulerChaos:
    def test_expired_deadline_shed_at_admission(self, tiny_engine_factory):
        """An already-expired request is shed at admission — finish
        "expired", deadline_shed counted, and NO prefill dispatch spent
        on it — while a live request admits normally."""
        from symmetry_tpu.engine.scheduler import Scheduler

        sched = Scheduler(tiny_engine_factory())
        sched.start()
        try:
            results, completed = drive_scheduler(sched, [
                {"max_new_tokens": 4,
                 "deadline_at": time.monotonic() - 1.0},
                {"max_new_tokens": 4,
                 "deadline_at": time.monotonic() + 300.0},
            ])
            assert completed[0] and completed[1]
            expired = results[0][-1]
            assert expired.finish_reason == "expired"
            assert "deadline expired" in expired.error
            assert results[1][-1].finish_reason in ("stop", "length")
            stats = sched.stats()
            assert stats["deadline_shed"] == 1
            # the shed request never reached a device dispatch
            assert stats["admit_dispatches"] == 1
        finally:
            sched.stop()

    def test_admit_seam_error_fails_one_request(self, tiny_engine_factory):
        """`scheduler.admit=error@once`: exactly the first request dies
        with the injected error event; the next admits and streams."""
        from symmetry_tpu.engine.scheduler import Scheduler

        FAULTS.load({"scheduler.admit": "error(injected-admit)@once"})
        sched = Scheduler(tiny_engine_factory())
        sched.start()
        try:
            results, completed = drive_scheduler(sched, [
                {"max_new_tokens": 4}, {"max_new_tokens": 4}])
            assert completed[0] and completed[1]
            assert results[0][-1].finish_reason == "error"
            assert "injected-admit" in results[0][-1].error
            assert results[1][-1].finish_reason in ("stop", "length")
        finally:
            sched.stop()

    def test_admit_seam_drop_loses_request_silently(self,
                                                    tiny_engine_factory):
        """`scheduler.admit=drop_frame@once`: the request vanishes with
        no terminal event — the lost-work mode the supervisor's watchdog
        (and stream timeouts) exist to catch — without disturbing its
        neighbors."""
        from symmetry_tpu.engine.scheduler import Scheduler

        from symmetry_tpu.engine.engine import SamplingParams
        from symmetry_tpu.engine.scheduler import GenRequest

        FAULTS.load({"scheduler.admit": "drop_frame@once"})
        sched = Scheduler(tiny_engine_factory())
        sched.start()
        try:
            results = {0: [], 1: []}
            done = {0: threading.Event(), 1: threading.Event()}
            for i in range(2):
                def emit(ev, i=i):
                    results[i].append(ev)
                    if ev.done:
                        done[i].set()
                sched.submit(GenRequest(
                    prompt_ids=list(b"req %d" % i),
                    sampling=SamplingParams(), max_new_tokens=4,
                    emit=emit, id=f"r{i}"))
            # The survivor completing proves the scheduler processed the
            # whole inbox — THEN the dropped request getting nothing in
            # its wake is conclusive, not a racing still-queued read.
            assert done[1].wait(60)
            assert results[1][-1].finish_reason in ("stop", "length")
            assert not done[0].wait(0.5)
            assert results[0] == []
        finally:
            sched.stop()


class TestInprocChaos:
    """The inproc tpu_native path under injected faults (satellite:
    echo + inproc harness must exercise the fault layer without a TPU)."""

    def _inproc_cfg(self):
        # Mirrors tests/test_e2e_tpu_native.py exactly so the compiled
        # tiny-engine programs come from the shared compile cache.
        return ConfigManager(config={
            "name": "inproc-chaos", "public": False,
            "serverKey": "00" * 32, "modelName": "tiny:chaos",
            "apiProvider": "tpu_native", "dataCollectionEnabled": False,
            "tpu": {"model_preset": "tiny", "dtype": "float32",
                    "max_batch_size": 4, "max_seq_len": 128,
                    "prefill_buckets": [32, 64],
                    "engine_isolation": "inproc"},
        })

    def test_dispatch_fault_and_deadline_inproc(self):
        async def main():
            backend = TpuNativeBackend(self._inproc_cfg())
            await backend.start()
            try:
                # A clean stream first (the engine works).
                text = await collect_stream(backend, max_tokens=4)
                assert isinstance(text, str)
                # backend.dispatch error: surfaces as InjectedFault from
                # the backend seam (the provider maps it to a dropped
                # peer — its own test lives with the network suite).
                FAULTS.load({"backend.dispatch": "error(injected)@once"})
                with pytest.raises(InjectedFault):
                    await collect_stream(backend, max_tokens=4)
                # An effectively-zero deadline is shed at admission and
                # surfaces as the terminal deadline error.
                with pytest.raises(BackendDeadlineError):
                    async for _chunk in backend.stream(InferenceRequest(
                            messages=[{"role": "user", "content": "x"}],
                            max_tokens=4, deadline_s=1e-9)):
                        pass
                # the engine is unharmed
                text = await collect_stream(backend, max_tokens=4)
                assert isinstance(text, str)
            finally:
                await backend.stop()

        run(main())
