"""Golden tests against a REAL transformers-written checkpoint.

Round-2 verdict gap: load_checkpoint was only ever tested against
checkpoints written by our own save_checkpoint, so a transposition or
naming error that cancels on the round-trip would pass. Here the
checkpoint is authored by `transformers.LlamaForCausalLM.save_pretrained`
and the logits are compared against transformers' own forward — the
formats and semantics are pinned by an independent implementation
(reference capability: the north star serves HF weights directly,
BASELINE.json; loader: engine/weights.py).

Everything runs on CPU with a tiny model; transformers is baked into the
image and never touches the network.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from symmetry_tpu.engine.weights import load_checkpoint  # noqa: E402
from symmetry_tpu.models.llama import forward, init_cache  # noqa: E402


def make_hf_model():
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attention_bias=False,
    )
    torch.manual_seed(7)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf_ckpt")
    model = make_hf_model()
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


class TestGoldenLogits:
    def test_logits_match_transformers(self, hf_checkpoint):
        path, model = hf_checkpoint
        params, config = load_checkpoint(path, dtype=jnp.float32)
        assert config.num_layers == 2
        assert config.num_kv_heads == 2

        ids = np.array([[3, 17, 91, 200, 5, 44, 8, 120, 7, 63]], np.int32)
        with torch.no_grad():
            want = model(torch.from_numpy(ids).long()).logits.numpy()

        cache = init_cache(config, 1, 32, jnp.float32)
        got, _ = forward(params, config, jnp.asarray(ids), cache)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=2e-4, atol=2e-4)

    def test_decode_continuation_matches(self, hf_checkpoint):
        """Prefill + one-token-at-a-time decode against the growing cache
        must match transformers' full-sequence forward at every step —
        catches RoPE-offset and cache-masking disagreements the one-shot
        logits test can't."""
        path, model = hf_checkpoint
        params, config = load_checkpoint(path, dtype=jnp.float32)

        prompt = [3, 17, 91, 200, 5]
        cache = init_cache(config, 1, 32, jnp.float32)
        logits, cache = forward(
            params, config, jnp.asarray([prompt], jnp.int32), cache)
        seq = list(prompt)
        ours = [int(jnp.argmax(logits[0, -1]))]
        for _ in range(5):
            seq.append(ours[-1])
            logits, cache = forward(
                params, config,
                jnp.asarray([[ours[-1]]], jnp.int32), cache)
            ours.append(int(jnp.argmax(logits[0, -1])))

        with torch.no_grad():
            out = model.generate(
                torch.tensor([prompt]).long(), max_new_tokens=6,
                do_sample=False, use_cache=True,
                pad_token_id=0)
        want = out[0, len(prompt):].tolist()
        assert ours == want

    def test_engine_serves_hf_checkpoint(self, hf_checkpoint):
        """The serving engine (prefill buckets + slot cache + greedy
        sampling) over the loaded checkpoint reproduces transformers'
        greedy continuation token-for-token."""
        from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
        from symmetry_tpu.engine.tokenizer import ByteTokenizer

        path, model = hf_checkpoint
        params, config = load_checkpoint(path, dtype=jnp.float32)
        engine = InferenceEngine(
            config, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            prefill_buckets=(16,), cache_dtype=jnp.float32)

        prompt = [3, 17, 91, 200, 5]
        first = engine.prefill_and_insert(0, prompt, SamplingParams())
        got = [first]
        for _ in range(5):
            got.append(int(engine.decode_step()[0]))

        with torch.no_grad():
            out = model.generate(
                torch.tensor([prompt]).long(), max_new_tokens=6,
                do_sample=False, use_cache=True, pad_token_id=0)
        assert got == out[0, len(prompt):].tolist()


class TestHFTokenizerReal:
    @pytest.fixture(scope="class")
    def tokenizer_dir(self, tmp_path_factory):
        """A REAL tokenizers-library tokenizer.json (byte-level BPE trained
        on a tiny corpus) + tokenizer_config.json with a chat template —
        the file set AutoTokenizer loads offline."""
        tokenizers = pytest.importorskip("tokenizers")
        path = tmp_path_factory.mktemp("tok")
        tok = tokenizers.Tokenizer(tokenizers.models.BPE(unk_token=None))
        tok.pre_tokenizer = tokenizers.pre_tokenizers.ByteLevel(
            add_prefix_space=False)
        tok.decoder = tokenizers.decoders.ByteLevel()
        trainer = tokenizers.trainers.BpeTrainer(
            vocab_size=384, special_tokens=["<|bos|>", "<|eos|>"],
            initial_alphabet=tokenizers.pre_tokenizers.ByteLevel.alphabet())
        tok.train_from_iterator(
            ["hello world", "the quick brown fox", "symmetry on tpu",
             "user and assistant talk"], trainer)
        tok.save(str(path / "tokenizer.json"))
        (path / "tokenizer_config.json").write_text(json.dumps({
            "tokenizer_class": "PreTrainedTokenizerFast",
            "bos_token": "<|bos|>",
            "eos_token": "<|eos|>",
            "chat_template": (
                "{% for m in messages %}{{ m['role'] }}: {{ m['content'] }}"
                "\n{% endfor %}assistant: "),
        }))
        return str(path)

    def test_roundtrip_and_template(self, tokenizer_dir):
        from symmetry_tpu.engine.tokenizer import HFTokenizer

        tok = HFTokenizer(tokenizer_dir)
        ids = tok.encode("hello world", bos=False)
        assert ids and tok.decode(ids) == "hello world"
        chat = tok.apply_chat_template(
            [{"role": "user", "content": "hello"}])
        assert isinstance(chat, list) and chat
        assert "assistant" in tok.decode(chat)

    def test_stream_decoder_multibyte(self, tokenizer_dir):
        """Incremental decode must hold back incomplete UTF-8 sequences."""
        from symmetry_tpu.engine.tokenizer import HFTokenizer

        tok = HFTokenizer(tokenizer_dir)
        text = "héllo wörld"
        ids = tok.encode(text, bos=False)
        dec = tok.stream_decoder()
        out = "".join(dec.push(i) for i in ids) + dec.flush()
        assert out == text

    def test_engine_end_to_end_with_hf_tokenizer(self, hf_checkpoint,
                                                 tokenizer_dir):
        """Full serving slice: HF checkpoint + HF tokenizer through the
        scheduler produce the same text as transformers greedy decode of
        the same rendered chat prompt."""
        import threading

        from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
        from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
        from symmetry_tpu.engine.tokenizer import HFTokenizer

        path, model = hf_checkpoint
        tok = HFTokenizer(tokenizer_dir)
        params, config = load_checkpoint(path, dtype=jnp.float32)
        engine = InferenceEngine(
            config, params, tok, max_slots=2, max_seq_len=64,
            prefill_buckets=(32,), cache_dtype=jnp.float32)

        messages = [{"role": "user", "content": "hello"}]
        prompt_ids = [i % config.vocab_size
                      for i in tok.apply_chat_template(messages)]

        events = []
        done = threading.Event()

        def emit(ev):
            events.append(ev)
            if ev.done:
                done.set()

        sched = Scheduler(engine, debug_invariants=True)
        sched.submit(GenRequest(prompt_ids=prompt_ids,
                                sampling=SamplingParams(),
                                max_new_tokens=6, emit=emit, id="g"))
        sched.start()
        assert done.wait(120)
        sched.stop()
        got_text = "".join(ev.text for ev in events)

        with torch.no_grad():
            out = model.generate(
                torch.tensor([prompt_ids]).long(), max_new_tokens=6,
                do_sample=False, use_cache=True, pad_token_id=0)
        cont = out[0, len(prompt_ids):].tolist()
        # strip tokens from/after an EOS the engine would stop at
        if any(t in tok.eos_ids for t in cont):
            cut = next(i for i, t in enumerate(cont) if t in tok.eos_ids)
            cont = cont[:cut]
        want_text = tok.decode(cont)
        assert got_text.rstrip("�") == want_text.rstrip("�")


class TestGemmaGolden:
    """Gemma family: GeGLU + (1+w) RMSNorm + sqrt(hidden) embedding scale,
    validated against transformers' GemmaForCausalLM the same way the
    llama path is — independent implementation, same checkpoint."""

    @pytest.fixture(scope="class")
    def gemma_checkpoint(self, tmp_path_factory):
        cfg = transformers.GemmaConfig(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            head_dim=16,
            max_position_embeddings=128,
            rms_norm_eps=1e-6,
            rope_theta=10000.0,
            tie_word_embeddings=True,
            hidden_activation="gelu_pytorch_tanh",
        )
        torch.manual_seed(11)
        model = transformers.GemmaForCausalLM(cfg)
        model.eval()
        path = tmp_path_factory.mktemp("gemma_ckpt")
        model.save_pretrained(path, safe_serialization=True)
        return str(path), model

    def test_logits_match_transformers(self, gemma_checkpoint):
        path, model = gemma_checkpoint
        params, config = load_checkpoint(path, dtype=jnp.float32)
        assert config.hidden_act == "gelu_tanh"
        assert config.norm_plus_one and config.scale_embed
        assert config.tie_embeddings

        ids = np.array([[7, 201, 44, 13, 88, 156, 2, 99]], np.int32)
        with torch.no_grad():
            want = model(torch.from_numpy(ids).long()).logits.numpy()
        cache = init_cache(config, 1, 32, jnp.float32)
        got, _ = forward(params, config, jnp.asarray(ids), cache)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=3e-4, atol=3e-4)

    def test_greedy_continuation_matches(self, gemma_checkpoint):
        path, model = gemma_checkpoint
        params, config = load_checkpoint(path, dtype=jnp.float32)
        prompt = [7, 201, 44, 13, 88]
        cache = init_cache(config, 1, 32, jnp.float32)
        logits, cache = forward(
            params, config, jnp.asarray([prompt], jnp.int32), cache)
        ours = [int(jnp.argmax(logits[0, -1]))]
        for _ in range(5):
            logits, cache = forward(
                params, config, jnp.asarray([[ours[-1]]], jnp.int32), cache)
            ours.append(int(jnp.argmax(logits[0, 0])))
        with torch.no_grad():
            out = model.generate(
                torch.tensor([prompt]).long(), max_new_tokens=6,
                do_sample=False, use_cache=True, pad_token_id=0)
        assert ours == out[0, len(prompt):].tolist()
