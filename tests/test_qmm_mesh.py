"""Mesh-aware W8A16 packed layout (sharded fused dequant), CPU mesh.

conftest pins 8 virtual CPU devices for the whole suite, so every TP
degree here runs inside tier-1 — no subprocess, no TPU.

Four contracts:

* Leaf parity per TP degree: pack_quantized with mesh + axis names
  (column-parallel n_axis, row-parallel k_axis) routes qmatmul through
  the shard_map'd per-shard kernel, and the result must match the
  single-device numpy reference across the trunk shape families — the
  sharded pack changes the schedule, never the numbers. Row-parallel
  additionally pins the reduce-then-scale order (psum the f32 partials,
  scale after) against the same reference.
* Per-shard tileability fallback: a mesh axis that doesn't divide K/N
  ("shard_indivisible") or leaves an untileable per-shard dim
  ("shard_untileable") keeps the flat leaf + mixed dot, reported per
  leaf, never silently; a size-1 mesh axis degrades to the cheaper
  single-device dispatch.
* Engine TP=2: fused vs unfused greedy token identity on the same mesh,
  zero steady-state recompiles after warmup, packed-and-sharded leaves,
  and weight_stream_bytes_per_device strictly below the aggregate
  (TP actually divides the per-chip weight stream).
* Warm cache round-trip of the sharded packed tree: save unpacks tiles
  to the flat int8 layout (cache stays readable by non-fused builds),
  load with the mesh rebuilds sharded leaves, and re-packing reproduces
  the original tile layout bit for bit.

Plus the fit70b byte-table golden: the 70B int8 per-device table
(tools/fit70b.py, eval_shape only) must keep fitting v5e and keep the
per-leaf packability verdicts honest (trunk packed, lm_head degrading).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.engine.weights import load_warm_cache, save_warm_cache
from symmetry_tpu.models import init_params, param_logical_axes, preset
from symmetry_tpu.models.llama import pack_params, quantize_params
from symmetry_tpu.ops.quant import (
    PackedQuantizedTensor,
    QuantizedTensor,
    _pack_quantized_report,
    pack_quantized,
    pack_tree,
    qmatmul,
    quantize,
    unpack_quantized,
)
from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Trunk shape families whose K AND N stay tileable per shard at every
# degree tested (CPU tile floor 8): wq-like square, GQA narrow kv, FFN
# wide, ragged needing the small-tile fallback blocks.
MESH_SHAPES = (
    (16, 64, 64),
    (16, 64, 32),
    (32, 96, 512),
    (8, 192, 320),
)

TP_DEGREES = (1, 2, 4)


def _mesh(tp):
    return build_mesh(MeshSpec(data=1, model=tp))


def _case(m, k, n, seed=0):
    kx, kw = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(kx, (m, k), jnp.float32)
    w = jax.random.normal(kw, (k, n), jnp.float32) * 0.05
    return x, quantize(w)


def _reference_qmatmul(x: np.ndarray, qt) -> np.ndarray:
    acc = x.astype(np.float32) @ np.asarray(qt.q, np.float32)
    return (acc * np.asarray(qt.scale)[None, :]).astype(x.dtype)


class TestShardedLeafParity:
    @pytest.mark.parametrize("tp", TP_DEGREES)
    def test_column_parallel_parity(self, tp):
        """n_axis sharding (wq/wk/wv/wg/wu/lm_head): full K per shard,
        N-slice out, no collective."""
        for m, k, n in MESH_SHAPES:
            x, qt = _case(m, k, n, seed=m + k + n)
            pt = pack_quantized(qt, n_axis="model", mesh=_mesh(tp))
            assert isinstance(pt, PackedQuantizedTensor), (tp, m, k, n)
            if tp > 1:
                assert pt.n_axis == "model" and pt.mesh is not None
            else:
                # size-1 axis: the cheaper single-device dispatch
                assert pt.mesh is None and pt.n_axis is None
            got = np.asarray(qmatmul(x, pt))
            want = _reference_qmatmul(np.asarray(x), qt)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                       err_msg=f"tp={tp} {(m, k, n)}")

    @pytest.mark.parametrize("tp", TP_DEGREES)
    def test_row_parallel_parity(self, tp):
        """k_axis sharding (wo/wd): per-shard partials with the scale
        OFF, f32 psum, scale after — the unfused mixed dot's reduce
        order, so fused and unfused mesh builds agree token for token."""
        for m, k, n in MESH_SHAPES:
            x, qt = _case(m, k, n, seed=m * 7 + n)
            pt = pack_quantized(qt, k_axis="model", mesh=_mesh(tp))
            assert isinstance(pt, PackedQuantizedTensor), (tp, m, k, n)
            if tp > 1:
                assert pt.k_axis == "model" and pt.mesh is not None
            got = np.asarray(qmatmul(x, pt))
            want = _reference_qmatmul(np.asarray(x), qt)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                       err_msg=f"tp={tp} {(m, k, n)}")

    def test_sharded_3d_activation(self):
        """lax.scan strips the layers dim off activations, not the leaf
        aux — the same sharded leaf must serve 3-D activations."""
        x, qt = _case(16, 64, 96, seed=5)
        pt = pack_quantized(qt, n_axis="model", mesh=_mesh(2))
        x3 = x.reshape(4, 4, 64)
        got = qmatmul(x3, pt)
        assert got.shape == (4, 4, 96)
        np.testing.assert_allclose(
            np.asarray(got).reshape(16, 96),
            _reference_qmatmul(np.asarray(x), qt), rtol=1e-5, atol=1e-5)

    def test_unpack_roundtrip_sharded(self):
        _, qt = _case(8, 64, 64, seed=7)
        pt = pack_quantized(qt, n_axis="model", mesh=_mesh(4))
        back = unpack_quantized(pt)
        np.testing.assert_array_equal(np.asarray(back.q),
                                      np.asarray(qt.q))
        np.testing.assert_array_equal(np.asarray(back.scale),
                                      np.asarray(qt.scale))


class TestShardDegradeReasons:
    def test_shard_indivisible(self):
        """Mesh axis doesn't divide N at all: flat leaf + reason."""
        _, qt = _case(8, 64, 30, seed=1)
        leaf, reason = _pack_quantized_report(qt, n_axis="model",
                                              mesh=_mesh(4))
        assert isinstance(leaf, QuantizedTensor)
        assert reason == "shard_indivisible"

    def test_shard_untileable(self):
        """N divides across the mesh but the per-shard slice loses
        tileability (48/4 = 12, no block candidate divides it)."""
        _, qt = _case(8, 64, 48, seed=2)
        leaf, reason = _pack_quantized_report(qt, n_axis="model",
                                              mesh=_mesh(4))
        assert isinstance(leaf, QuantizedTensor)
        assert reason == "shard_untileable"

    def test_size_one_axis_packs_single_device(self):
        """model=1 shards nothing — the leaf must pack WITHOUT the mesh
        aux so it keeps the cheaper non-shard_map dispatch."""
        _, qt = _case(8, 64, 64, seed=3)
        leaf, reason = _pack_quantized_report(qt, n_axis="model",
                                              mesh=_mesh(1))
        assert reason is None
        assert isinstance(leaf, PackedQuantizedTensor)
        assert leaf.mesh is None and leaf.n_axis is None

    def test_pack_tree_reports_degrades(self):
        """pack_tree collects (path, reason) for every flat-stayed int8
        leaf — the engine books these into sym_qmm_fallback_total."""
        _, bad = _case(8, 64, 30, seed=4)
        _, good = _case(8, 64, 64, seed=5)
        kq, _ = jax.random.split(jax.random.key(6))
        stack = jax.random.normal(kq, (2, 2, 64, 64), jnp.float32)
        params = {"layers": {"wq": good, "wo": bad,
                             "wexp": quantize(stack)}}
        report = []
        pack_tree(params, ("wq", "wo", "wexp"),
                  axes={"wq": (None, "model"), "wo": (None, "model"),
                        "wexp": (None, "model")},
                  mesh=_mesh(4), report=report)
        assert isinstance(params["layers"]["wq"], PackedQuantizedTensor)
        assert isinstance(params["layers"]["wo"], QuantizedTensor)
        assert ("layers/wo", "shard_indivisible") in report
        assert ("layers/wexp", "expert_stack") in report
        assert not any(path.endswith("wq") for path, _ in report)


def _packed_leaves(tree):
    is_pqt = lambda x: isinstance(x, PackedQuantizedTensor)  # noqa: E731
    return [l for l in jax.tree.leaves(tree, is_leaf=is_pqt)
            if is_pqt(l)]


def _mesh_engine(fused):
    cfg = preset("tiny-mha")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    mesh = _mesh(2)
    params = jax.device_put(
        params, shardings_for(param_logical_axes(cfg), mesh))
    params = quantize_params(params)
    eng = InferenceEngine(cfg, params, ByteTokenizer(), mesh=mesh,
                          max_slots=2, max_seq_len=64,
                          prefill_buckets=(16,),
                          cache_dtype=jnp.float32, fused_dequant=fused)
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def mesh_engines():
    return _mesh_engine(True), _mesh_engine(False)


class TestMeshEngine:
    def test_params_packed_and_sharded(self, mesh_engines):
        fused, unfused = mesh_engines
        packed = _packed_leaves(fused.params)
        assert packed, "fused mesh engine packed no leaves"
        # megatron TP: both column- (n_axis) and row-parallel (k_axis)
        # leaves must be present, each carrying the mesh
        assert any(p.n_axis == "model" for p in packed)
        assert any(p.k_axis == "model" for p in packed)
        assert all(p.mesh is not None for p in packed
                   if p.n_axis or p.k_axis)
        assert not _packed_leaves(unfused.params)

    def test_greedy_identity_fused_vs_unfused(self, mesh_engines):
        fused, unfused = mesh_engines
        toks = []
        for eng in mesh_engines:
            t = [eng.prefill_and_insert(0, list(b"mesh parity"),
                                        SamplingParams())]
            for _ in range(8):
                t.append(int(eng.decode_steps()[0][0]))
            toks.append(t)
        assert toks[0] == toks[1], toks

    def test_zero_steady_state_recompiles(self, mesh_engines):
        for eng in mesh_engines:
            warm = eng.compile_cache_sizes()
            eng.prefill_and_insert(0, list(b"steady"), SamplingParams())
            eng.decode_steps()
            eng.prefill_and_insert(1, list(b"state"), SamplingParams())
            for _ in range(3):
                eng.decode_steps()
            assert eng.compile_cache_sizes() == warm

    def test_weight_stream_bytes_per_device(self, mesh_engines):
        fused, _ = mesh_engines
        agg = fused.weight_stream_bytes()
        dev = fused.weight_stream_bytes_per_device()
        # TP=2 with replicated norms: strictly less than the aggregate,
        # no better than a perfect 2-way split
        assert agg / 2 <= dev < agg, (agg, dev)


class TestWarmCacheMeshRoundTrip:
    def test_sharded_packed_roundtrip(self, tmp_path):
        cfg = preset("tiny-mha")
        mesh = _mesh(2)
        params = init_params(cfg, jax.random.key(3), jnp.float32)
        params = jax.device_put(
            params, shardings_for(param_logical_axes(cfg), mesh))
        params = quantize_params(params)
        params = pack_params(params, config=cfg, mesh=mesh)
        orig_packed = _packed_leaves(params)
        assert orig_packed

        save_warm_cache(str(tmp_path), params, cfg,
                        dtype=jnp.float32, quantize=True)
        warm = load_warm_cache(str(tmp_path), dtype=jnp.float32,
                               quantize=True, mesh=mesh)
        assert warm is not None
        wparams, wcfg = warm
        assert wcfg == cfg

        # The cache stores the FLAT int8 layout (tile geometry is a
        # kernel tuning detail — non-fused builds read the same file),
        # so the loaded tree has QuantizedTensor leaves, sharded.
        assert not _packed_leaves(wparams)

        def flat(tree):
            is_pqt = lambda x: isinstance(  # noqa: E731
                x, PackedQuantizedTensor)
            return [unpack_quantized(l) if is_pqt(l) else l
                    for l in jax.tree.leaves(tree, is_leaf=is_pqt)]

        a, b = flat(params), flat(wparams)
        assert len(jax.tree.leaves(a)) == len(jax.tree.leaves(b))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            assert x.dtype == y.dtype
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

        # Re-packing the loaded tree reproduces the tile layout bit for
        # bit — a warm restart lands on the identical packed program.
        repacked = pack_params(wparams, config=cfg, mesh=mesh)
        new_packed = _packed_leaves(repacked)
        assert len(new_packed) == len(orig_packed)
        for p, q in zip(orig_packed, new_packed):
            assert (p.k_axis, p.n_axis) == (q.k_axis, q.n_axis)
            np.testing.assert_array_equal(np.asarray(p.q),
                                          np.asarray(q.q))


class TestFit70bTable:
    @pytest.fixture(scope="class")
    def table(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "fit70b", os.path.join(REPO, "tools", "fit70b.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.per_device_table(2, 8)

    def test_fits_v5e(self, table):
        """The round-19 headline: 70B int8 + 8x8192 int8 KV on 16 chips
        lands under 10 GB/device — fits v5e's 16 GB with headroom."""
        assert table["fits"]["v5e"] is True
        assert table["total_bytes_per_device"] < 10e9
        # params ~8.96 GB/dev, KV ~0.69 GB/dev — a drifting init or
        # sharding rule shows up here before anyone rents a slice
        assert 8.5e9 < table["params_bytes_per_device"] < 9.5e9
        assert 0.4e9 < table["kv_bytes_per_device"] < 1.0e9

    def test_trunk_packs_lm_head_degrades(self, table):
        rows = {r["leaf"].rsplit("/", 1)[-1]: r for r in table["leaves"]}
        for name in ("wq", "wk", "wv", "wo", "wg", "wu", "wd"):
            assert rows[name]["layout"].startswith("packed:"), rows[name]
        # 128256 / 8 = 16032 misses the 128-lane N floor: the honest
        # degrade, counted, not silent
        assert rows["lm_head"]["layout"] == "mixed_dot:shard_untileable"
        assert rows["wq"]["shard_parts"] == 8

    def test_packed_share_dominates(self, table):
        """Most per-device weight bytes ride the fused kernel."""
        assert (table["packed_bytes_per_device"]
                > 0.5 * table["params_bytes_per_device"])
