"""Identity: seeded keypairs, discovery keys, signatures."""

from symmetry_tpu.identity import Identity, discovery_key


def test_seeded_keypair_deterministic():
    # Capability parity: reference seeds identity from a fixed 32-byte buffer
    # (src/provider.ts:41-43) — same seed must yield the same identity.
    a = Identity.from_seed(b"\x01" * 32)
    b = Identity.from_seed(b"\x01" * 32)
    c = Identity.from_seed(b"\x02" * 32)
    assert a.public_key == b.public_key
    assert a.public_key != c.public_key


def test_from_name_deterministic_and_secret_salted():
    assert Identity.from_name("node").public_key == Identity.from_name("node").public_key
    assert (
        Identity.from_name("node", secret=b"s1").public_key
        != Identity.from_name("node", secret=b"s2").public_key
    )


def test_sign_verify():
    ident = Identity.generate()
    sig = ident.sign(b"challenge-bytes")
    assert Identity.verify(b"challenge-bytes", sig, ident.public_key)
    assert not Identity.verify(b"other-bytes", sig, ident.public_key)
    assert not Identity.verify(b"challenge-bytes", b"\x00" * 64, ident.public_key)
    assert not Identity.verify(b"challenge-bytes", sig, Identity.generate().public_key)
    assert not Identity.verify(b"challenge-bytes", sig, b"short")


def test_discovery_key_stable_and_hiding():
    ident = Identity.from_seed(b"\x07" * 32)
    dk = discovery_key(ident.public_key)
    assert len(dk) == 32
    assert dk == ident.discovery_key
    assert dk != ident.public_key  # topic must not reveal the key


def test_repr_leaks_nothing():
    ident = Identity.from_seed(b"\x09" * 32)
    assert "private" not in repr(ident).lower()
    assert ident.public_hex[:16] in repr(ident)
