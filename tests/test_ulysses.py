"""Ulysses sequence parallelism (parallel/ulysses.py) vs single-device
reference over a context-sharded CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.parallel import MeshSpec, build_mesh
from symmetry_tpu.parallel.ulysses import ulysses_attention
from tests.test_ops import naive_attention


@pytest.fixture(scope="module")
def sp_mesh():
    return build_mesh(MeshSpec(context=4))


class TestUlyssesAttention:
    @pytest.mark.parametrize("nq,nkv", [(8, 8), (8, 4)])
    def test_matches_naive(self, sp_mesh, nq, nkv):
        rng = np.random.default_rng(1)
        B, S, D = 2, 64, 16
        q = rng.normal(size=(B, S, nq, D)).astype(np.float32)
        k = rng.normal(size=(B, S, nkv, D)).astype(np.float32)
        v = rng.normal(size=(B, S, nkv, D)).astype(np.float32)
        seq_lens = np.array([64, 41], np.int32)

        got = np.asarray(ulysses_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(seq_lens), sp_mesh))
        q_pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S))
        want = naive_attention(q, k, v, q_pos, seq_lens)
        for b in range(B):
            n = seq_lens[b]
            np.testing.assert_allclose(got[b, :n], want[b, :n],
                                       rtol=2e-4, atol=2e-4)
        assert not np.isnan(got).any()

    def test_matches_ring(self, sp_mesh):
        """Both SP schemes must compute the same attention."""
        from symmetry_tpu.parallel.ring import ring_attention

        rng = np.random.default_rng(2)
        B, S, H, K, D = 1, 32, 8, 4, 8
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, K, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, K, D)), jnp.float32)
        seq_lens = jnp.asarray([S], jnp.int32)
        a = np.asarray(ulysses_attention(q, k, v, seq_lens, sp_mesh))
        b = np.asarray(ring_attention(q, k, v, seq_lens, sp_mesh))
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)

    def test_sharded_jit_keeps_sequence_sharding(self, sp_mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        B, S, H, D = 1, 32, 4, 8
        q = jax.device_put(
            jnp.ones((B, S, H, D)),
            NamedSharding(sp_mesh, P(None, "context", None, None)))
        seq_lens = jnp.asarray([S], jnp.int32)
        out = jax.jit(
            lambda q: ulysses_attention(q, q, q, seq_lens, sp_mesh))(q)
        assert out.shape == (B, S, H, D)
        assert out.sharding.spec == P(None, "context", None, None)

    def test_rejects_indivisible_heads(self, sp_mesh):
        q = jnp.ones((1, 32, 2, 8))  # 2 heads, 4 shards
        with pytest.raises(ValueError, match="divisible by shards"):
            ulysses_attention(q, q, q, jnp.asarray([32]), sp_mesh)

    def test_rejects_indivisible_sequence(self, sp_mesh):
        q = jnp.ones((1, 30, 8, 8))
        with pytest.raises(ValueError, match="not divisible"):
            ulysses_attention(q, q, q, jnp.asarray([30]), sp_mesh)


class TestModelIntegration:
    def test_forward_hidden_ulysses_matches_ring(self, sp_mesh):
        """Full-model context-parallel prefill: sp_mode='ulysses' must
        produce the same hidden states as the ring scheme."""
        from symmetry_tpu.models import init_cache, init_params
        from symmetry_tpu.models.llama import ModelConfig, forward_hidden

        # 8 kv heads so 4-way head scatter divides evenly
        cfg = ModelConfig(vocab_size=128, hidden_size=64, num_layers=2,
                          num_heads=8, num_kv_heads=8, intermediate_size=96,
                          rope_theta=10000.0, max_position=128)
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(3).integers(0, 128, (2, 32)), jnp.int32)
        seq_lens = jnp.asarray([32, 20], jnp.int32)

        def run(mode):
            h, _ = forward_hidden(
                params, cfg, tokens, init_cache(cfg, 2, 32, jnp.float32),
                seq_lens=seq_lens, prefill_flash=True,
                ring_mesh=sp_mesh, sp_mode=mode)
            return np.asarray(h)

        ring, uly = run("ring"), run("ulysses")
        for b, n in enumerate([32, 20]):
            np.testing.assert_allclose(uly[b, :n], ring[b, :n],
                                       rtol=2e-4, atol=2e-4)
