"""Warm weight cache (engine/weights.py save/load_warm_cache): restarts
skip the HF-layout conversion + quantization entirely (SURVEY §5.4).

The cache must reproduce the cold-loaded tree EXACTLY — same dtypes
(including bfloat16 via the uint16-view trick), same quantized leaves,
same shardings — and be strictly advisory: absent/corrupt caches fall
back to the cold path.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.weights import (
    load_checkpoint,
    load_warm_cache,
    save_checkpoint,
    save_warm_cache,
)
from symmetry_tpu.models import init_params, preset
from symmetry_tpu.models.llama import quantize_params


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("warm_ckpt"))
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(9), jnp.float32)
    save_checkpoint(path, params, cfg)
    return path


def trees_equal(a, b) -> bool:
    la = jax.tree.leaves(a)
    lb = jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        x.dtype == y.dtype and np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


class TestWarmCache:
    def test_roundtrip_dense_bf16(self, checkpoint):
        params, cfg = load_checkpoint(checkpoint, dtype=jnp.bfloat16)
        save_warm_cache(checkpoint, params, cfg, dtype=jnp.bfloat16,
                        quantize=False)
        warm = load_warm_cache(checkpoint, dtype=jnp.bfloat16,
                               quantize=False)
        assert warm is not None
        wparams, wcfg = warm
        assert wcfg == cfg
        assert trees_equal(params, wparams)

    def test_roundtrip_quantized(self, checkpoint):
        params, cfg = load_checkpoint(checkpoint, dtype=jnp.bfloat16)
        params = quantize_params(params)
        save_warm_cache(checkpoint, params, cfg, dtype=jnp.bfloat16,
                        quantize=True)
        warm = load_warm_cache(checkpoint, dtype=jnp.bfloat16,
                               quantize=True)
        assert warm is not None
        wparams, _ = warm
        assert trees_equal(params, wparams)
        # quantized leaves come back as QuantizedTensor
        from symmetry_tpu.ops.quant import QuantizedTensor

        assert isinstance(wparams["layers"]["wq"], QuantizedTensor)
        assert wparams["layers"]["wq"].q.dtype == jnp.int8

    def test_missing_and_corrupt_fall_back(self, checkpoint, tmp_path):
        assert load_warm_cache(str(tmp_path), dtype=jnp.bfloat16,
                               quantize=False) is None
        # corrupt meta → None, not an exception
        params, cfg = load_checkpoint(checkpoint, dtype=jnp.float32)
        save_warm_cache(checkpoint, params, cfg, dtype=jnp.float32,
                        quantize=False)
        from symmetry_tpu.engine.weights import _warm_path

        meta = os.path.join(_warm_path(checkpoint, jnp.float32, False),
                            "meta.json")
        with open(meta, "w", encoding="utf-8") as fh:
            fh.write("{broken")
        assert load_warm_cache(checkpoint, dtype=jnp.float32,
                               quantize=False) is None

    def test_stale_cache_invalidated_on_checkpoint_change(
            self, tmp_path_factory):
        """Overwriting the checkpoint (same path) must invalidate the
        cache — serving a fine-tune's path with the OLD weights would be
        silent corruption."""
        path = str(tmp_path_factory.mktemp("stale_ckpt"))
        cfg = preset("tiny")
        save_checkpoint(path, init_params(cfg, jax.random.key(1),
                                          jnp.float32), cfg)
        params, cfg2 = load_checkpoint(path, dtype=jnp.float32)
        save_warm_cache(path, params, cfg2, dtype=jnp.float32,
                        quantize=False)
        assert load_warm_cache(path, dtype=jnp.float32,
                               quantize=False) is not None
        # new weights at the same path (distinct mtime/size fingerprint)
        import time as _t

        _t.sleep(0.01)
        save_checkpoint(path, init_params(cfg, jax.random.key(2),
                                          jnp.float32), cfg)
        os.utime(os.path.join(path, "model.safetensors"))
        assert load_warm_cache(path, dtype=jnp.float32,
                               quantize=False) is None

    def test_sharded_load(self, checkpoint):
        from symmetry_tpu.parallel import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(data=1, model=2), jax.devices()[:2])
        params, cfg = load_checkpoint(checkpoint, dtype=jnp.float32)
        params = quantize_params(params)
        save_warm_cache(checkpoint, params, cfg, dtype=jnp.float32,
                        quantize=True)
        warm = load_warm_cache(checkpoint, dtype=jnp.float32,
                               quantize=True, mesh=mesh)
        assert warm is not None
        wparams, _ = warm
        assert trees_equal(params, wparams)
        # heads dim of wq is sharded over the model axis
        shard = wparams["layers"]["wq"].q.sharding
        assert "model" in getattr(shard, "spec", ())

    def test_engine_uses_warm_cache(self, checkpoint):
        """from_tpu_config writes the cache on first load and reads it on
        the second — and both engines produce identical greedy tokens."""
        from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
        from symmetry_tpu.engine.weights import _warm_path
        from symmetry_tpu.provider.config import ConfigManager

        cfg = ConfigManager(config={
            "name": "warm", "public": False, "serverKey": "00" * 32,
            "modelName": "tiny:warm", "apiProvider": "tpu_native",
            "dataCollectionEnabled": False,
            "tpu": {"checkpoint_path": checkpoint, "dtype": "float32",
                    "max_batch_size": 2, "max_seq_len": 64,
                    "prefill_buckets": [16], "decode_block": 1},
        })
        e1 = InferenceEngine.from_tpu_config(cfg.tpu)
        assert os.path.exists(
            _warm_path(checkpoint, jnp.float32, False))
        e2 = InferenceEngine.from_tpu_config(cfg.tpu)
        prompt = list(b"warm start")
        t1 = [e1.prefill_and_insert(0, prompt, SamplingParams())]
        t2 = [e2.prefill_and_insert(0, prompt, SamplingParams())]
        for _ in range(4):
            t1.append(int(e1.decode_step()[0]))
            t2.append(int(e2.decode_step()[0]))
        assert t1 == t2
