"""Kademlia DHT (network/dht.py): multi-node announce/lookup over loopback.

The reference's hyperdht capability (SURVEY §2.2): providers announce
under a 32-byte topic, clients look the topic up without a central server.
These tests run a real multi-node network in one event loop over UDP
loopback — the SURVEY §4 multi-node-without-a-cluster technique.
"""

import asyncio

import pytest

from symmetry_tpu.identity import Identity
from symmetry_tpu.network.dht import DHTNode, RoutingTable, NodeInfo


def run(coro, timeout=60):
    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(coro, timeout))


async def make_network(n):
    """n nodes (each with a signing identity), bootstrapped off node 0."""
    nodes = [DHTNode(identity=Identity.generate()) for _ in range(n)]
    await nodes[0].start("127.0.0.1", 0)
    boot = [("127.0.0.1", nodes[0].port)]
    for node in nodes[1:]:
        await node.start("127.0.0.1", 0, bootstrap=boot)
    return nodes


async def stop_all(nodes):
    for node in nodes:
        await node.stop()


class TestRoutingTable:
    def test_add_and_closest_ordering(self):
        self_id = bytes(32)
        table = RoutingTable(self_id)
        ids = [bytes([i]) + bytes(31) for i in range(1, 9)]
        for i, nid in enumerate(ids):
            table.add(NodeInfo(node_id=nid, host="h", port=i))
        target = ids[3]
        closest = table.closest(target, 3)
        assert closest[0].node_id == ids[3]
        assert len(table) == 8

    def test_self_never_added(self):
        self_id = bytes(32)
        table = RoutingTable(self_id)
        table.add(NodeInfo(node_id=self_id, host="h", port=1))
        assert len(table) == 0

    def test_refresh_updates_address(self):
        table = RoutingTable(bytes(32))
        nid = bytes([1]) + bytes(31)
        table.add(NodeInfo(node_id=nid, host="old", port=1))
        table.add(NodeInfo(node_id=nid, host="new", port=2))
        assert len(table) == 1
        assert table.closest(nid, 1)[0].host == "new"


class TestDHTNetwork:
    def test_announce_lookup_across_nodes(self):
        async def main():
            nodes = await make_network(6)
            try:
                ident = nodes[1].identity
                topic = ident.discovery_key
                # publicKey filled in (and signed) from the node identity
                stored = await nodes[1].announce(
                    topic, {"address": "tcp://10.0.0.5:9000"})
                assert stored >= 1
                # every OTHER node can discover it
                for node in (nodes[3], nodes[5]):
                    peers = await node.lookup(topic)
                    assert any(p["publicKey"] == ident.public_hex
                               for p in peers), peers
            finally:
                await stop_all(nodes)

        run(main())

    def test_lookup_missing_topic_empty(self):
        async def main():
            nodes = await make_network(4)
            try:
                peers = await nodes[2].lookup(b"\xaa" * 32)
                assert peers == []
            finally:
                await stop_all(nodes)

        run(main())

    def test_multiple_providers_same_topic(self):
        async def main():
            nodes = await make_network(5)
            try:
                topic = b"\x42" * 32
                for i in (1, 2, 3):
                    await nodes[i].announce(topic, {"address": f"tcp://p{i}"})
                peers = await nodes[4].lookup(topic)
                want = {nodes[i].identity.public_hex for i in (1, 2, 3)}
                assert {p["publicKey"] for p in peers} >= want
            finally:
                await stop_all(nodes)

        run(main())

    def test_survives_node_death(self):
        async def main():
            nodes = await make_network(6)
            try:
                topic = b"\x07" * 32
                await nodes[1].announce(topic, {"address": "a"})
                # kill two non-announcing nodes; lookup still resolves
                await nodes[2].stop()
                await nodes[3].stop()
                peers = await nodes[5].lookup(topic)
                pk = nodes[1].identity.public_hex
                assert any(p["publicKey"] == pk for p in peers)
            finally:
                await stop_all([nodes[0], nodes[1], nodes[4], nodes[5]])

        run(main())

    def test_one_node_network_self_resolves(self):
        async def main():
            node = DHTNode(identity=Identity.generate())
            await node.start("127.0.0.1", 0)
            try:
                topic = b"\x01" * 32
                await node.announce(topic, {"address": "self"})
                peers = await node.lookup(topic)
                assert peers
                assert peers[0]["publicKey"] == node.identity.public_hex
            finally:
                await node.stop()

        run(main())


class TestServerlessDiscovery:
    def test_client_discovers_provider_via_dht_and_chats(self):
        """Full serverless path: provider announces on the DHT, client
        resolves it by public key and streams a chat with NO central
        server in the loop (the reference's direct-connection mode plus
        hyperdht discovery)."""
        async def main():
            from symmetry_tpu.client.client import SymmetryClient
            from symmetry_tpu.provider.config import ConfigManager
            from symmetry_tpu.provider.provider import SymmetryProvider
            from symmetry_tpu.transport.tcp import TcpTransport

            boot = DHTNode()
            await boot.start("127.0.0.1", 0)

            cfg = ConfigManager(config={
                "name": "dht-prov", "public": False,
                "serverKey": "00" * 32,
                "modelName": "tiny:dht", "apiProvider": "echo",
                "dataCollectionEnabled": False,
                "dht": {"host": "127.0.0.1",
                        "bootstrap": [f"127.0.0.1:{boot.port}"]},
            })
            ident = Identity.from_name("dht-prov-ident")
            transport = TcpTransport()
            provider = SymmetryProvider(cfg, transport=transport,
                                        identity=ident)
            await provider.start("127.0.0.1:0")
            try:
                client = SymmetryClient(Identity.from_name("dht-cli"),
                                        TcpTransport())
                details = await client.discover(
                    ident.public_key, [f"127.0.0.1:{boot.port}"])
                assert details.model_name == "tiny:dht"
                session = await client.connect(details)
                text = await session.chat_text(
                    [{"role": "user", "content": "dht!"}])
                assert text  # echo backend streams something back
                await session.close()
            finally:
                await provider.stop(drain_timeout_s=3)
                await boot.stop()

        run(main())

    def test_discover_unknown_provider_raises(self):
        async def main():
            from symmetry_tpu.client.client import ClientError, SymmetryClient
            from symmetry_tpu.transport.tcp import TcpTransport

            boot = DHTNode()
            await boot.start("127.0.0.1", 0)
            try:
                client = SymmetryClient(Identity.from_name("dht-cli2"),
                                        TcpTransport())
                with pytest.raises(ClientError, match="not found"):
                    await client.discover(Identity.generate().public_key,
                                          [f"127.0.0.1:{boot.port}"])
            finally:
                await boot.stop()

        run(main())


class TestUnannounce:
    def test_unannounce_removes_remote_records(self):
        async def main():
            nodes = await make_network(4)
            try:
                topic = b"\x09" * 32
                pk = nodes[1].identity.public_hex
                await nodes[1].announce(topic, {"address": "a"})
                assert any(p["publicKey"] == pk
                           for p in await nodes[3].lookup(topic))
                await nodes[1].unannounce(topic)
                assert await nodes[3].lookup(topic) == []
            finally:
                await stop_all(nodes)

        run(main())

    def test_restart_overwrites_stale_record(self):
        """Same publicKey re-announced from a NEW DHT node (provider
        restart) must replace the old record, not accumulate beside it."""
        async def main():
            nodes = await make_network(4)
            try:
                topic = b"\x0a" * 32
                await nodes[1].announce(topic, {"address": "old:1"})
                # restarted provider: SAME identity (persisted seed), new
                # random DHT node id
                fresh = DHTNode(identity=nodes[1].identity)
                await fresh.start("127.0.0.1", 0,
                                  bootstrap=[("127.0.0.1", nodes[0].port)])
                await fresh.announce(topic, {"address": "new:2"})
                peers = await nodes[3].lookup(topic)
                pk = nodes[1].identity.public_hex
                mine = [p for p in peers if p["publicKey"] == pk]
                assert len(mine) == 1, peers
                assert mine[0]["address"] == "new:2"
                await fresh.stop()
            finally:
                await stop_all(nodes)

        run(main())


class TestSignedRecords:
    """Round-2 verdict: the DHT control plane was unauthenticated — anyone
    could announce under any key or evict someone else's record. publicKey
    records are now Ed25519-signed and verified on store AND unannounce."""

    def test_forged_unannounce_rejected(self):
        async def main():
            nodes = await make_network(4)
            try:
                topic = b"\x0b" * 32
                victim_pk = nodes[1].identity.public_hex
                await nodes[1].announce(topic, {"address": "live:1"})
                assert any(p["publicKey"] == victim_pk
                           for p in await nodes[3].lookup(topic))
                # Attacker (nodes[2], different identity) sends unannounce
                # for the victim's record: unsigned AND wrongly-signed both
                # rejected; the record must survive.
                import time as _time
                from symmetry_tpu.network.dht import _unannounce_sig_msg
                for node in nodes[0], nodes[3]:
                    await nodes[2]._rpc(
                        ("127.0.0.1", node.port),
                        {"type": "unannounce", "topic": topic.hex(),
                         "key": victim_pk})
                    ts = _time.time()
                    await nodes[2]._rpc(
                        ("127.0.0.1", node.port),
                        {"type": "unannounce", "topic": topic.hex(),
                         "key": victim_pk, "ts": round(ts, 3),
                         "sig": nodes[2].identity.sign(_unannounce_sig_msg(
                             topic.hex(), victim_pk, ts)).hex()})
                assert any(p["publicKey"] == victim_pk
                           for p in await nodes[3].lookup(topic))
                # The real owner's signed unannounce still works.
                await nodes[1].unannounce(topic)
                assert await nodes[3].lookup(topic) == []
            finally:
                await stop_all(nodes)

        run(main())

    def test_forged_announce_rejected(self):
        """Nobody can plant a record under a publicKey they don't hold."""
        async def main():
            nodes = await make_network(3)
            try:
                topic = b"\x0c" * 32
                victim_pk = nodes[1].identity.public_hex
                import time as _time
                ts = round(_time.time(), 3)
                resp = await nodes[2]._rpc(
                    ("127.0.0.1", nodes[0].port),
                    {"type": "announce", "topic": topic.hex(),
                     "payload": {"address": "evil:666",
                                 "publicKey": victim_pk,
                                 "ts": ts, "sig": "ab" * 64}})
                assert resp.get("type") == "rejected"
                peers = await nodes[2].lookup(topic)
                assert not any(p["publicKey"] == victim_pk for p in peers)
            finally:
                await stop_all(nodes)

        run(main())

    def test_stale_signature_rejected(self):
        """A record whose timestamp is far outside the skew window is
        rejected even with a valid signature (replay of a captured
        announce)."""
        async def main():
            from symmetry_tpu.network.dht import (
                MAX_SIG_SKEW_S, _announce_sig_msg)
            import time as _time

            nodes = await make_network(3)
            try:
                topic = b"\x0d" * 32
                ident = nodes[1].identity
                ts = _time.time() - MAX_SIG_SKEW_S - 60
                payload = {"address": "old", "publicKey": ident.public_hex,
                           "ts": round(ts, 3)}
                payload["sig"] = ident.sign(
                    _announce_sig_msg(topic.hex(), payload, ts)).hex()
                resp = await nodes[1]._rpc(
                    ("127.0.0.1", nodes[0].port),
                    {"type": "announce", "topic": topic.hex(),
                     "payload": payload})
                assert resp.get("type") == "rejected"
            finally:
                await stop_all(nodes)

        run(main())

    def test_unsigned_publickey_announce_requires_identity(self):
        async def main():
            node = DHTNode()  # no identity
            await node.start("127.0.0.1", 0)
            try:
                with pytest.raises(ValueError, match="identity"):
                    await node.announce(b"\x0e" * 32,
                                        {"address": "x", "publicKey": "ab"})
            finally:
                await node.stop()

        run(main())

    def test_replayed_announce_after_unannounce_rejected(self):
        """A captured announce replayed after the owner's unannounce must
        not resurrect the record (tombstone fence)."""
        async def main():
            nodes = await make_network(3)
            try:
                topic = b"\x0f" * 32
                pk = nodes[1].identity.public_hex
                await nodes[1].announce(topic, {"address": "live"})
                # capture the signed record as a storing node holds it
                stored = nodes[0]._store.get(topic.hex(), {}).get(pk)
                assert stored is not None
                captured = dict(stored[0])
                await nodes[1].unannounce(topic)
                assert await nodes[2].lookup(topic) == []
                # attacker replays the captured (validly signed) announce
                resp = await nodes[2]._rpc(
                    ("127.0.0.1", nodes[0].port),
                    {"type": "announce", "topic": topic.hex(),
                     "payload": captured})
                assert resp.get("type") == "rejected"
                assert await nodes[2].lookup(topic) == []
                # but a FRESH re-announce from the real owner works
                await nodes[1].announce(topic, {"address": "back"})
                peers = await nodes[2].lookup(topic)
                assert any(p["publicKey"] == pk for p in peers)
            finally:
                await stop_all(nodes)

        run(main())
