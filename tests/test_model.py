"""Model correctness: HF parity, decode==prefill, padding, tied embeddings.

The HF parity test is the anchor: a tiny random-init torch LlamaForCausalLM
and our model given the same weights must produce the same logits, proving
the RoPE convention, GQA grouping, norm placement, and weight-map transposes
all match — which is what makes real llama3 checkpoints loadable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.weights import convert_hf_state_dict
from symmetry_tpu.models import (
    forward,
    init_cache,
    init_params,
    preset,
)


def make_hf_tiny(tie=False):
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=tie,
        attention_bias=False, mlp_bias=False, max_position_embeddings=512,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg).eval()
    state = {k: v.detach().numpy() for k, v in model.state_dict().items()
             if not k.endswith("rotary_emb.inv_freq")}
    return model, state


class TestHFParity:
    def test_logits_match_transformers(self):
        torch = pytest.importorskip("torch")
        model, state = make_hf_tiny()
        config = preset("tiny")
        params = jax.tree.map(jnp.asarray, convert_hf_state_dict(state, config))

        tokens = np.random.default_rng(0).integers(0, 512, size=(2, 9))
        with torch.no_grad():
            want = model(torch.tensor(tokens)).logits.numpy()

        cache = init_cache(config, batch=2, capacity=16, dtype=jnp.float32)
        got, _ = forward(params, config, jnp.asarray(tokens, jnp.int32), cache)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)

    def test_tied_embeddings_parity(self):
        torch = pytest.importorskip("torch")
        model, state = make_hf_tiny(tie=True)
        from dataclasses import replace

        config = replace(preset("tiny"), tie_embeddings=True)
        state.pop("lm_head.weight", None)
        params = jax.tree.map(jnp.asarray, convert_hf_state_dict(state, config))

        tokens = np.random.default_rng(1).integers(0, 512, size=(1, 5))
        with torch.no_grad():
            want = model(torch.tensor(tokens)).logits.numpy()
        cache = init_cache(config, batch=1, capacity=8, dtype=jnp.float32)
        got, _ = forward(params, config, jnp.asarray(tokens, jnp.int32), cache)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


class TestDecode:
    def setup_method(self):
        self.config = preset("tiny")
        self.params = init_params(self.config, jax.random.key(0), jnp.float32)

    def test_decode_matches_full_prefill(self):
        """prefill(prefix) + N decode steps == one full-sequence forward."""
        cfg, params = self.config, self.params
        tokens = np.random.default_rng(2).integers(0, 512, size=(2, 8)).astype(np.int32)

        full_cache = init_cache(cfg, 2, 16, jnp.float32)
        full_logits, _ = forward(params, cfg, jnp.asarray(tokens), full_cache)

        cache = init_cache(cfg, 2, 16, jnp.float32)
        _, cache = forward(params, cfg, jnp.asarray(tokens[:, :5]), cache)
        step_logits = []
        for i in range(5, 8):
            logits, cache = forward(params, cfg, jnp.asarray(tokens[:, i:i+1]), cache)
            step_logits.append(np.asarray(logits[:, 0]))
        for j, i in enumerate(range(5, 8)):
            np.testing.assert_allclose(
                step_logits[j], np.asarray(full_logits[:, i]),
                rtol=1e-4, atol=1e-4)

    def test_padded_prefill_matches_unpadded(self):
        """Ragged batch: logits at valid positions unaffected by padding."""
        cfg, params = self.config, self.params
        rng = np.random.default_rng(3)
        a = rng.integers(0, 512, size=6).astype(np.int32)

        cache1 = init_cache(cfg, 1, 16, jnp.float32)
        want, _ = forward(params, cfg, jnp.asarray(a[None, :]), cache1)

        padded = np.zeros((1, 10), np.int32)
        padded[0, :6] = a
        cache2 = init_cache(cfg, 1, 16, jnp.float32)
        got, cache2 = forward(params, cfg, jnp.asarray(padded),
                              cache2, seq_lens=jnp.asarray([6]))
        np.testing.assert_allclose(np.asarray(got[:, :6]), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
        assert int(cache2.lengths[0]) == 6

    def test_ragged_decode_batch(self):
        """Two slots at different cache lengths decode correctly together."""
        cfg, params = self.config, self.params
        rng = np.random.default_rng(4)
        sa = rng.integers(0, 512, size=7).astype(np.int32)
        sb = rng.integers(0, 512, size=3).astype(np.int32)

        # Independent single-sample ground truths.
        def solo(seq):
            cache = init_cache(cfg, 1, 16, jnp.float32)
            logits, _ = forward(params, cfg, jnp.asarray(seq[None, :]), cache)
            return np.asarray(logits[0, -1])

        # Batched: prefill each into its slot (padded), then one decode step.
        cache = init_cache(cfg, 2, 16, jnp.float32)
        padded = np.zeros((2, 6), np.int32)
        padded[0, :6] = sa[:6]
        padded[1, :2] = sb[:2]
        _, cache = forward(params, cfg, jnp.asarray(padded), cache,
                           seq_lens=jnp.asarray([6, 2]))
        last = np.stack([sa[6:7], sb[2:3]])
        logits, cache = forward(params, cfg, jnp.asarray(last), cache)
        np.testing.assert_allclose(np.asarray(logits[0, 0]), solo(sa),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(logits[1, 0]), solo(sb),
                                   rtol=1e-4, atol=1e-4)


class TestJit:
    def test_forward_jits_and_caches(self):
        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        jitted = jax.jit(lambda p, t, c: forward(p, cfg, t, c))
        cache = init_cache(cfg, 1, 16, jnp.float32)
        tokens = jnp.ones((1, 4), jnp.int32)
        l1, cache = jitted(params, tokens, cache)
        l2, cache = jitted(params, tokens, cache)  # same shapes: cache hit
        assert l1.shape == (1, 4, cfg.vocab_size)
        assert jitted._cache_size() == 1


class TestGemmaFamily:
    """Gemma semantics (GeGLU, (1+w) norms, scaled embeddings) through
    the shared decoder and the serving engine; golden parity with
    transformers lives in test_weights_real.py."""

    def test_gemma_flags_change_outputs(self):
        import dataclasses

        cfg = preset("tiny-gemma")
        params = init_params(cfg, jax.random.key(3), jnp.float32)
        ids = jnp.asarray([[5, 9, 2, 77]], jnp.int32)
        cache = init_cache(cfg, 1, 16, jnp.float32)
        out_gemma, _ = forward(params, cfg, ids, cache)
        # same weights interpreted WITHOUT the gemma flags must differ —
        # guards against the flags being silently ignored
        plain = dataclasses.replace(cfg, hidden_act="silu",
                                    norm_plus_one=False, scale_embed=False)
        cache = init_cache(cfg, 1, 16, jnp.float32)
        out_plain, _ = forward(params, plain, ids, cache)
        assert not np.allclose(np.asarray(out_gemma), np.asarray(out_plain))

    def test_engine_serves_tiny_gemma(self):
        from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
        from symmetry_tpu.engine.tokenizer import ByteTokenizer

        cfg = preset("tiny-gemma")
        params = init_params(cfg, jax.random.key(4), jnp.float32)
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            prefill_buckets=(16,), cache_dtype=jnp.float32)
        first = engine.prefill_and_insert(0, list(b"gemma!"),
                                          SamplingParams())
        toks = [first] + [int(engine.decode_step()[0]) for _ in range(4)]
        assert all(0 <= t < cfg.vocab_size for t in toks)

        # greedy engine decode == plain forward loop (family-specific
        # layers must not break the continuous-batching contract)
        cache = init_cache(cfg, 1, 64, jnp.float32)
        logits, cache = forward(params, cfg,
                                jnp.asarray([list(b"gemma!")], jnp.int32),
                                cache)
        want = [int(jnp.argmax(logits[0, -1]))]
        for _ in range(4):
            logits, cache = forward(
                params, cfg, jnp.asarray([[want[-1]]], jnp.int32), cache)
            want.append(int(jnp.argmax(logits[0, 0])))
        assert toks == want
