"""Fault-injection layer unit tests (utils/faults.py).

The injector is the foundation the whole chaos suite stands on, so its
trigger semantics (once / nth / every / probability), action semantics
(drop / error / delay), config surfaces (env string and config mapping),
and — critically — its unconfigured no-op cost are pinned here.
"""

import asyncio
import time

import pytest

from symmetry_tpu.utils.faults import (
    FAULTS,
    FaultInjector,
    InjectedFault,
    parse_rule,
)


@pytest.fixture(autouse=True)
def clean_global_faults():
    """The module-global injector must never leak rules across tests."""
    FAULTS.clear()
    yield
    FAULTS.clear()


class TestParsing:
    def test_actions(self):
        assert parse_rule("a.b", "crash").kind == "crash"
        r = parse_rule("a.b", "hang(30)")
        assert (r.kind, r.seconds) == ("hang", 30.0)
        assert parse_rule("a.b", "hang").seconds == 3600.0  # default wedge
        r = parse_rule("a.b", "delay(0.25)")
        assert (r.kind, r.seconds) == ("delay", 0.25)
        r = parse_rule("a.b", "error(boom town)")
        assert (r.kind, r.message) == ("error", "boom town")
        assert parse_rule("a.b", "drop_frame").kind == "drop_frame"

    def test_triggers(self):
        assert parse_rule("s", "crash").trigger == "always"
        assert parse_rule("s", "crash@once").trigger == "once"
        r = parse_rule("s", "crash@nth=7")
        assert (r.trigger, r.n) == ("nth", 7)
        r = parse_rule("s", "drop_frame@every=3")
        assert (r.trigger, r.n) == ("every", 3)
        r = parse_rule("s", "error@p=0.25")
        assert (r.trigger, r.prob) == ("p", 0.25)

    def test_invalid_specs_fail_loudly(self):
        for bad in ("explode", "crash@sometimes", "delay", "crash(5)",
                    "drop_frame@nth=0"):
            with pytest.raises(ValueError):
                parse_rule("s", bad)
        inj = FaultInjector()
        with pytest.raises(ValueError):
            inj.load("no-equals-sign")
        with pytest.raises(ValueError):
            inj.load(42)
        assert not inj.enabled  # a rejected load arms nothing

    def test_env_string_and_mapping_forms(self):
        inj = FaultInjector()
        inj.load("a.b=drop_frame@every=2; c.d=error(x)@once")
        inj.load({"e.f": "delay(0.01)", "g.h": ["crash@nth=9",
                                                "drop_frame@p=0.5"]})
        seams = {r.seam for r in inj.rules()}
        assert seams == {"a.b", "c.d", "e.f", "g.h"}
        assert inj.enabled
        inj.clear()
        assert not inj.enabled and not inj.rules()


class TestTriggers:
    def test_once_fires_exactly_once(self):
        inj = FaultInjector()
        inj.load("s=drop_frame@once")
        assert [inj.point("s") for _ in range(4)] == [True, False,
                                                     False, False]

    def test_nth_fires_exactly_on_the_nth_hit(self):
        inj = FaultInjector()
        inj.load("s=drop_frame@nth=3")
        assert [inj.point("s") for _ in range(5)] == [False, False, True,
                                                     False, False]

    def test_every_n(self):
        inj = FaultInjector()
        inj.load("s=drop_frame@every=2")
        assert [inj.point("s") for _ in range(6)] == [False, True] * 3

    def test_probability_bounds(self):
        inj = FaultInjector()
        inj.load("always=drop_frame@p=1.0; never=drop_frame@p=0.0")
        assert all(inj.point("always") for _ in range(8))
        assert not any(inj.point("never") for _ in range(8))

    def test_unknown_seam_never_fires(self):
        inj = FaultInjector()
        inj.load("s=drop_frame")
        assert inj.point("other.seam") is False

    def test_counters(self):
        inj = FaultInjector()
        inj.load("s=drop_frame@every=2")
        for _ in range(4):
            inj.point("s")
        assert inj.counters() == {"s": {"hits": 4, "fired": 2}}

    def test_multiple_rules_one_seam_budget_not_consumed_by_winner(self):
        """First armed rule wins a hit; later rules record the hit but
        keep their trigger budget — `fired` counts APPLIED actions only,
        which is what the chaos assertions read."""
        inj = FaultInjector()
        inj.load({"s": ["drop_frame@once", "drop_frame@every=2"]})
        # hit 1: rule A (@once) fires; rule B's budget untouched
        # hit 2: A spent; B sees its 2nd hit → every=2 fires
        # hit 3: nothing; hit 4: B fires again
        assert [inj.point("s") for _ in range(4)] == [True, True,
                                                     False, True]
        assert inj.counters() == {"s": {"hits": 8, "fired": 3}}


class TestActions:
    def test_error_raises_injected_fault(self):
        inj = FaultInjector()
        inj.load("s=error(kapow)")
        with pytest.raises(InjectedFault, match="kapow"):
            inj.point("s")

    def test_error_default_message_names_the_seam(self):
        inj = FaultInjector()
        inj.load("host.pipe_write=error")
        with pytest.raises(InjectedFault, match="host.pipe_write"):
            inj.point("host.pipe_write")

    def test_delay_blocks_then_proceeds(self):
        inj = FaultInjector()
        inj.load("s=delay(0.05)")
        t0 = time.monotonic()
        assert inj.point("s") is False
        assert time.monotonic() - t0 >= 0.04

    def test_apoint_async_delay_and_drop(self):
        inj = FaultInjector()
        inj.load("s=delay(0.05)@once; d=drop_frame")

        async def main():
            t0 = time.monotonic()
            assert await inj.apoint("s") is False
            assert time.monotonic() - t0 >= 0.04
            assert await inj.apoint("d") is True
            with pytest.raises(InjectedFault):
                inj.load("e=error")
                await inj.apoint("e")

        asyncio.new_event_loop().run_until_complete(main())


class TestNoopOverhead:
    def test_unconfigured_injector_is_a_noop(self):
        """The contract instrumented hot paths rely on: with nothing
        armed, a seam costs one attribute read + one early return. 200k
        calls in well under half a second leaves an order of magnitude
        of CI-machine headroom."""
        inj = FaultInjector()
        assert inj.enabled is False
        t0 = time.perf_counter()
        for _ in range(200_000):
            if inj.enabled and inj.point("host.pipe_write"):
                pass
        assert time.perf_counter() - t0 < 0.5
        # and point() itself stays cheap when called without the guard
        t0 = time.perf_counter()
        for _ in range(200_000):
            inj.point("host.pipe_write")
        assert time.perf_counter() - t0 < 0.5

    def test_global_injector_starts_disabled_without_env(self):
        # The autouse fixture cleared it; this is the state every
        # production process without SYMMETRY_FAULTS runs in.
        assert FAULTS.enabled is False
        assert FAULTS.point("any.seam") is False
