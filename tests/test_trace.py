"""Tracing + metrics (utils/trace.py): histograms, spans, provider stats."""

import time

from symmetry_tpu.utils.trace import Histogram, Tracer


class TestHistogram:
    def test_percentiles_exact_within_reservoir(self):
        h = Histogram()
        for ms in range(1, 1001):
            h.observe(ms / 1000.0)
        assert h.count == 1000
        p50, p90, p99 = h.percentile(50), h.percentile(90), h.percentile(99)
        # 1000 samples fit the reservoir: percentiles are EXACT order
        # statistics, not bucket edges (the round-4 p50==p99 artifact).
        assert p50 == 0.5
        assert p90 == 0.9
        assert p99 == 0.99
        assert p50 < p90 < p99

    def test_reservoir_estimate_beyond_cap(self):
        h = Histogram(reservoir=256)
        for i in range(10_000):
            h.observe((i % 1000 + 1) / 1000.0)
        assert h.count == 10_000
        p50 = h.percentile(50)
        # Uniform subsample of a uniform(0.001, 1.0) stream: the estimate
        # must land near the true median, far tighter than a 1.58x bucket.
        assert 0.35 <= p50 <= 0.65
        assert h.percentile(1) < p50 < h.percentile(99)

    def test_empty(self):
        h = Histogram()
        assert h.percentile(50) is None
        assert h.mean is None
        d = h.to_dict()
        assert d["count"] == 0 and d["p99"] is None

    def test_extremes_clamped(self):
        h = Histogram()
        h.observe(1e-9)   # below lowest edge
        h.observe(1e6)    # above highest edge
        assert h.count == 2
        assert h.min == 1e-9 and h.max == 1e6
        assert h.percentile(100) == 1e6


class TestTracer:
    def test_span_records_and_aggregates(self):
        tr = Tracer()
        with tr.span("prefill", request_id="r1", bucket=128):
            time.sleep(0.01)
        with tr.span("prefill", request_id="r2", bucket=512):
            pass
        spans = tr.export()
        assert len(spans) == 2
        assert spans[0]["name"] == "prefill"
        assert spans[0]["bucket"] == 128
        assert spans[0]["duration_s"] >= 0.01
        assert tr.export(request_id="r2")[0]["bucket"] == 512
        assert tr.stats()["prefill_s"]["count"] == 2

    def test_disabled_is_noop(self):
        tr = Tracer()
        tr.enabled = False
        with tr.span("x"):
            pass
        tr.record("y", 0.0, 1.0)
        assert tr.export() == []
        assert tr.stats() == {}

    def test_ring_bounded(self):
        tr = Tracer(capacity=8)
        for i in range(20):
            tr.record("s", 0.0, 0.001, request_id=str(i))
        spans = tr.export()
        assert len(spans) == 8
        assert spans[0]["request_id"] == "12"  # oldest retained

    def test_annotate_inside_span(self):
        tr = Tracer()
        with tr.span("gen") as attrs:
            attrs["tokens"] = 42
        assert tr.export()[0]["tokens"] == 42
