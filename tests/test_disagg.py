"""Disaggregated prefill/decode: KV handoff frames, roles, and identity.

Covers the acceptance surface of the disagg PR:

  - frame codec: round-trip across GQA kv_dim shapes, int8-quantized
    caches (scale planes), bf16 payloads, routing-only (p == 0) frames;
    truncated/corrupt/wrong-version/wrong-shape frames are REJECTED
    (versioned header + crc — bad frames must never adopt as KV)
  - broker: per-role config derivation (role pinned, decode tier's
    prefix cache defaulted, per-tier faults), request-state migration
    (adopt op carries sampling/max_new, deadline rebased by prefill-tier
    time), unknown/cancelled ids dropped
  - engine roles: construction contracts (decode needs the prefix
    store, prefill needs a chunk size, mesh refused), adoption rejects
    geometry/dtype/alignment mismatches, budget rejection degrades to
    full prefill
  - THE contract: greedy decode is token-identical between a unified
    engine and an in-process prefill-role → frames → decode-role pair,
    across short (routing-only), single-dispatch, and multi-chunk
    prompts — with per-role scheduler accounting (a decode host books
    adoption, not admission prefill; a prefill host books handoffs)
  - host wire ops: the prefill host's handoff frame emit (counters,
    short-prompt fast path) and the decode host's adopt op (corrupt
    frame → error event, never a submit)
  - the CROSS-MACHINE handoff link (engine/disagg/net.py): envelope
    reassembly over a transport that fragments and coalesces
    arbitrarily, corrupt-transfer nak → retransmit, mid-stream
    disconnect → zero partial adoptions, credit-window backpressure,
    ack-timeout retry exhaustion → fail, link clock reconciliation
    under deliberate skew, and the disagg.net.* fault seams
"""

import asyncio
import json
import random
import struct
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.disagg import (
    DEFAULT_DECODE_PREFIX_MB,
    FrameError,
    HandoffBroker,
    decode_kv_handoff,
    derive_role_config,
    encode_kv_handoff,
)
from symmetry_tpu.engine.engine import EngineError, InferenceEngine, SamplingParams
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import init_params, preset


# ---------------------------------------------------------------------
# Frame codec


def gqa_arrays(L=3, K=2, D=8, p=16, dtype=np.float32):
    """kv_heads != heads — the GQA shape the frames must round-trip."""
    rng = np.random.default_rng(0)
    return {
        "k": rng.standard_normal((L, 1, p, K, D)).astype(dtype),
        "v": rng.standard_normal((L, 1, p, K, D)).astype(dtype),
    }


class TestFrames:
    def test_roundtrip_gqa_f32(self):
        arrays = gqa_arrays()
        tokens = list(range(20))
        buf = encode_kv_handoff("req-1", tokens, 16, arrays)
        h = decode_kv_handoff(buf)
        assert h.request_id == "req-1"
        assert h.tokens == tuple(tokens)
        assert h.p == 16 and not h.kv_quant
        np.testing.assert_array_equal(h.arrays["k"], arrays["k"])
        np.testing.assert_array_equal(h.arrays["v"], arrays["v"])

    def test_roundtrip_int8_quantized(self):
        L, K, p = 2, 4, 8
        arrays = {
            "k": np.arange(L * p * K * 4, dtype=np.int8).reshape(
                L, 1, p, K, 4),
            "v": np.ones((L, 1, p, K, 4), np.int8),
            "k_scale": np.full((L, 1, K, p), 0.5, np.float32),
            "v_scale": np.full((L, 1, K, p), 0.25, np.float32),
        }
        buf = encode_kv_handoff("q", list(range(10)), p, arrays,
                                kv_quant=True)
        h = decode_kv_handoff(buf)
        assert h.kv_quant
        np.testing.assert_array_equal(h.arrays["k_scale"],
                                      arrays["k_scale"])
        assert h.arrays["k"].dtype == np.int8

    def test_roundtrip_bf16(self):
        import ml_dtypes

        arrays = {k: v.astype(ml_dtypes.bfloat16)
                  for k, v in gqa_arrays(p=8).items()}
        h = decode_kv_handoff(encode_kv_handoff("b", list(range(9)), 8,
                                                arrays))
        assert h.arrays["k"].dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(h.arrays["k"], arrays["k"])

    def test_routing_only_frame(self):
        h = decode_kv_handoff(encode_kv_handoff("r0", [1, 2, 3], 0, None))
        assert h.p == 0 and h.arrays == {} and h.tokens == (1, 2, 3)

    def test_multi_chunk_prefix_roundtrip(self):
        """A prefix spanning several prefill chunks is still ONE frame —
        the codec carries whatever p the prefill tier built."""
        arrays = gqa_arrays(p=48)  # 6 chunks at chunk=8
        h = decode_kv_handoff(encode_kv_handoff("m", list(range(50)), 48,
                                                arrays))
        assert h.p == 48 and h.arrays["k"].shape[2] == 48

    def test_truncated_rejected(self):
        buf = encode_kv_handoff("t", list(range(20)), 16, gqa_arrays())
        for cut in (0, 4, 10, len(buf) // 2, len(buf) - 1):
            with pytest.raises(FrameError):
                decode_kv_handoff(buf[:cut])

    def test_corrupt_payload_rejected(self):
        buf = bytearray(encode_kv_handoff("c", list(range(20)), 16,
                                          gqa_arrays()))
        buf[len(buf) // 2] ^= 0xFF
        with pytest.raises(FrameError, match="checksum"):
            decode_kv_handoff(bytes(buf))

    def test_wrong_version_rejected(self):
        buf = bytearray(encode_kv_handoff("v", list(range(20)), 16,
                                          gqa_arrays()))
        buf[4:6] = struct.pack("<H", 99)
        with pytest.raises(FrameError, match="version"):
            decode_kv_handoff(bytes(buf))

    def test_bad_magic_rejected(self):
        buf = encode_kv_handoff("m", list(range(20)), 16, gqa_arrays())
        with pytest.raises(FrameError, match="magic"):
            decode_kv_handoff(b"NOPE" + buf[4:])

    def test_shape_and_plane_validation(self):
        arrays = gqa_arrays(p=16)
        # p axis disagreeing with meta is caught at decode
        bad = dict(arrays)
        bad["k"] = arrays["k"][:, :, :8]
        with pytest.raises(FrameError):
            decode_kv_handoff(encode_kv_handoff("s", list(range(20)), 16,
                                                bad))
        # encoder itself enforces plane presence
        with pytest.raises(ValueError, match="missing KV planes"):
            encode_kv_handoff("s", list(range(20)), 16, {"k": arrays["k"]})
        # quantized frame without scale planes
        with pytest.raises(ValueError, match="missing KV planes"):
            encode_kv_handoff("s", list(range(20)), 16, arrays,
                              kv_quant=True)
        # p beyond the prompt
        with pytest.raises(ValueError):
            encode_kv_handoff("s", [1, 2], 16, arrays)

    def test_decoder_shape_validation(self):
        """A structurally-valid frame whose meta lies about shapes is
        still rejected (defense against a buggy/mismatched peer)."""
        arrays = gqa_arrays(p=16)
        buf = encode_kv_handoff("d", list(range(20)), 16, arrays)
        # splice the meta: claim p=8 while arrays carry 16
        from symmetry_tpu.engine.disagg import encode_frame

        meta = {"id": "d", "tokens": list(range(20)), "p": 8,
                "kv_quant": False}
        forged = encode_frame(meta, arrays)
        with pytest.raises(FrameError):
            decode_kv_handoff(forged)
        assert decode_kv_handoff(buf).p == 16  # control


# ---------------------------------------------------------------------
# Broker


BASE_CFG = {
    "name": "p", "public": True, "serverKey": "00" * 32,
    "modelName": "tiny:test", "apiProvider": "tpu_native",
    "tpu": {"role": "disagg", "model_preset": "tiny",
            "max_batch_size": 4,
            "disagg": {"prefill": {"faults": {"disagg.handoff": "crash"}},
                       "decode": {"max_batch_size": 8}}},
}


class TestBroker:
    def test_derive_role_configs(self):
        pre = derive_role_config(BASE_CFG, "prefill")
        dec = derive_role_config(BASE_CFG, "decode")
        assert pre["tpu"]["role"] == "prefill"
        assert dec["tpu"]["role"] == "decode"
        # per-tier overrides land in the tier's tpu section only
        assert pre["tpu"]["max_batch_size"] == 4
        assert dec["tpu"]["max_batch_size"] == 8
        # tier faults land TOP-LEVEL on that host only
        assert pre["faults"] == {"disagg.handoff": "crash"}
        assert "faults" not in dec
        # BOTH tiers get a prefix-cache budget by default: decode for
        # adopt-by-reference, prefill so its radix tree has something
        # to gossip for cache-affine pool routing
        assert dec["tpu"]["prefix_cache_mb"] == DEFAULT_DECODE_PREFIX_MB
        assert pre["tpu"]["prefix_cache_mb"] == DEFAULT_DECODE_PREFIX_MB
        # neither derived config keeps the disagg mapping (a tier host
        # must not recurse)
        assert "disagg" not in pre["tpu"] and "disagg" not in dec["tpu"]
        # the source mapping is untouched
        assert BASE_CFG["tpu"]["role"] == "disagg"

    def test_adopt_op_migrates_state_and_rebases_deadline(self):
        broker = HandoffBroker()
        broker.note_submit("r1", {
            "op": "submit", "id": "r1", "messages": [{"role": "user"}],
            "max_new": 32, "sampling": {"temperature": 0.5, "seed": 7},
            "trace": "t-1", "deadline_s": 10.0})
        time.sleep(0.05)
        op = broker.adopt_op({"id": "r1", "p": 16, "nbytes": 1234,
                              "frame": "QUJD"})
        assert op["op"] == "adopt" and op["id"] == "r1"
        assert op["frame"] == "QUJD"
        assert op["max_new"] == 32
        assert op["sampling"] == {"temperature": 0.5, "seed": 7}
        assert op["trace"] == "t-1"
        assert "messages" not in op  # tokens ride the frame
        assert 9.0 < op["deadline_s"] < 10.0  # rebased, not reset
        assert broker.counters["handoff_frames"] == 1
        assert broker.counters["handoff_bytes"] == 1234
        assert broker.counters["prefix_tokens"] == 16
        assert broker.pending == 0
        assert broker.prefill_tier_hist.count == 1

    def test_unknown_or_forgotten_id_drops_frame(self):
        broker = HandoffBroker()
        assert broker.adopt_op({"id": "ghost", "p": 0}) is None
        broker.note_submit("r2", {"max_new": 8})
        broker.forget("r2")  # cancelled before the handoff came back
        assert broker.adopt_op({"id": "r2", "p": 0}) is None
        assert broker.counters["dropped"] == 1
        stats = broker.stats()
        assert stats["submitted"] == 1 and stats["pending"] == 0

    def test_fail_all_clears_pending(self):
        broker = HandoffBroker()
        broker.note_submit("a", {})
        broker.note_submit("b", {})
        broker.fail_all()
        assert broker.pending == 0
        assert broker.counters["dropped"] == 2


# ---------------------------------------------------------------------
# Engine roles + the token-identity contract


@pytest.fixture(scope="module")
def setup():
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    return cfg, params


def make_engine(cfg, params, role="unified", cache_mb=16, chunk=8,
                slots=4, **kw):
    return InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=slots, max_seq_len=64,
        prefill_buckets=(16, 32), cache_dtype=jnp.float32,
        prefill_chunk=chunk, prefix_cache_bytes=int(cache_mb * 2**20),
        prefix_block_tokens=8, role=role, **kw)


def drive(sched, prompts, max_new=6, timeout=120):
    """Submit greedy requests; returns [(text, finish_reason, error)]."""
    done = threading.Event()
    out = [None] * len(prompts)
    texts = [[] for _ in prompts]
    remaining = [len(prompts)]

    def mk(i):
        def emit(ev):
            texts[i].append(ev.text)
            if ev.done:
                out[i] = ("".join(texts[i]), ev.finish_reason, ev.error)
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.set()
        return emit

    for i, ids in enumerate(prompts):
        sched.submit(GenRequest(prompt_ids=list(ids),
                                sampling=SamplingParams(),
                                max_new_tokens=max_new, emit=mk(i),
                                id=f"r{i}"))
    assert done.wait(timeout), f"streams incomplete: {out}"
    return out


def host_style_handoff(engine, slot, req, skip=()):
    """What the prefill host's sink does: extract the whole-block
    slot-lane KV and serialize it blockwise (the real sink lives in
    engine/host.py; this mirrors it so the identity test exercises the
    same frame path)."""
    n = len(req.prompt_ids)
    PB = engine.prefix_block
    p = PB * ((n - 1) // PB)
    arrays = None
    if p > 0:
        cache = engine.extract_slot_kv(slot, p)
        arrays = {"k": np.asarray(cache.k)[:, :, :p],
                  "v": np.asarray(cache.v)[:, :, :p]}
        if engine.kv_quant:
            arrays["k_scale"] = np.asarray(cache.k_scale)[:, :, :, :p]
            arrays["v_scale"] = np.asarray(cache.v_scale)[:, :, :, :p]
    return encode_kv_handoff(req.id, req.prompt_ids, p, arrays,
                             kv_quant=engine.kv_quant,
                             block_size=PB, skip=skip)


PROMPTS = [
    list(b"hello world prefix!"),            # 19 toks → p=16, 1 dispatch
    list(b"hi"),                             # 2 toks → p=0 routing-only
    list(b"a longer prompt that needs chunked prefill")[:30],  # p=24,
                                             # multi-chunk at chunk=8
    list(b"hello world prefill"),            # shares aligned prefix w/ #0
]


class TestRoleContracts:
    def test_bad_role_rejected(self, setup):
        cfg, params = setup
        with pytest.raises(EngineError, match="unknown engine role"):
            make_engine(cfg, params, role="disagg")

    def test_decode_role_requires_prefix_store(self, setup):
        cfg, params = setup
        with pytest.raises(EngineError, match="prefix cache"):
            make_engine(cfg, params, role="decode", cache_mb=0)

    def test_prefill_role_requires_chunk(self, setup):
        cfg, params = setup
        with pytest.raises(EngineError, match="prefill_chunk"):
            make_engine(cfg, params, role="prefill", chunk=None)

    def test_prefill_scheduler_requires_sink(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, role="prefill")
        with pytest.raises(ValueError, match="handoff sink"):
            Scheduler(engine)

    def test_adoption_rejects_mismatches(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, role="decode")
        good = gqa_arrays(L=cfg.num_layers, K=cfg.num_kv_heads,
                          D=cfg.dim_per_head, p=16)
        # wrong layer count
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16,
            gqa_arrays(L=cfg.num_layers + 1, K=cfg.num_kv_heads,
                       D=cfg.dim_per_head, p=16)))
        with pytest.raises(EngineError, match="shape"):
            engine.adopt_prefix(h)
        # wrong dtype (engine cache is f32 here)
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16,
            {k: v.astype(np.float16) for k, v in good.items()}))
        with pytest.raises(EngineError, match="dtype"):
            engine.adopt_prefix(h)
        # quantization mismatch
        qarr = {"k": np.zeros((cfg.num_layers, 1, 16, cfg.num_kv_heads,
                               cfg.dim_per_head), np.int8),
                "v": np.zeros((cfg.num_layers, 1, 16, cfg.num_kv_heads,
                               cfg.dim_per_head), np.int8),
                "k_scale": np.zeros((cfg.num_layers, 1, cfg.num_kv_heads,
                                     16), np.float32),
                "v_scale": np.zeros((cfg.num_layers, 1, cfg.num_kv_heads,
                                     16), np.float32)}
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16, qarr, kv_quant=True))
        with pytest.raises(EngineError, match="quantization"):
            engine.adopt_prefix(h)
        # non-whole-block prefix length: adoption FLOORS to whole
        # engine blocks (block is 8 here) instead of rejecting — a
        # shorter prefix is always causally sound
        mis = gqa_arrays(L=cfg.num_layers, K=cfg.num_kv_heads,
                         D=cfg.dim_per_head, p=12)
        h = decode_kv_handoff(encode_kv_handoff(
            "y", list(range(100, 120)), 12, mis))
        assert engine.adopt_prefix(h) is True
        assert engine.prefix_index.match_len(list(range(100, 120))) == 8
        # multi-block frame whose block size straddles the pool's
        # (bs=12 over PB=8): the tail block [24:36) must CLIP to the
        # floored run [24:32) — an unclipped assembly write would
        # broadcast-crash against the 32-capacity row
        mis = gqa_arrays(L=cfg.num_layers, K=cfg.num_kv_heads,
                         D=cfg.dim_per_head, p=36)
        h = decode_kv_handoff(encode_kv_handoff(
            "z", list(range(200, 236)), 36, mis, block_size=12))
        assert engine.adopt_prefix(h) is True
        assert engine.prefix_index.match_len(list(range(200, 236))) == 32
        # control: a well-formed frame adopts
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16, good))
        assert engine.adopt_prefix(h) is True
        assert engine.adopt_prefix(h) is True  # idempotent (resident)
        # manifest-only frame (every block skipped): adopted by
        # reference while resident...
        h_skip = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16, good, block_size=8, skip=[0, 1]))
        assert engine.adopt_prefix(h_skip) is True
        # ...but a fresh decode tier (nothing resident) cannot use it
        fresh = make_engine(cfg, params, role="decode")
        assert fresh.adopt_prefix(h_skip) is False


class TestDisaggIdentity:
    """THE acceptance contract: greedy disagg == greedy unified."""

    @pytest.fixture(scope="class")
    def reference(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, role="unified", cache_mb=0)
        engine.warmup()
        sched = Scheduler(engine)
        sched.start()
        try:
            return drive(sched, PROMPTS)
        finally:
            sched.stop()

    def test_greedy_token_identical_and_per_role_stats(self, setup,
                                                       reference):
        cfg, params = setup
        eng_p = make_engine(cfg, params, role="prefill")
        eng_p.warmup()
        eng_d = make_engine(cfg, params, role="decode")
        eng_d.warmup()

        frames: dict[str, bytes] = {}
        fallback_events = []

        def handoff(slot, req, first):
            frames[req.id] = host_style_handoff(eng_p, slot, req)

        sched_p = Scheduler(eng_p, handoff=handoff)
        sched_p.start()
        sched_d = Scheduler(eng_d)
        sched_d.start()
        try:
            # Tier 1: prefill-role admission builds KV and hands off.
            for i, ids in enumerate(PROMPTS):
                sched_p.submit(GenRequest(
                    prompt_ids=list(ids), sampling=SamplingParams(),
                    max_new_tokens=6,
                    emit=lambda ev: fallback_events.append(ev),
                    id=f"r{i}"))
            deadline = time.monotonic() + 120
            while len(frames) < len(PROMPTS):
                assert time.monotonic() < deadline, \
                    f"handoffs incomplete: {sorted(frames)}; " \
                    f"events={fallback_events}"
                time.sleep(0.02)
            ps = sched_p.stats()
            assert ps["role"] == "prefill"
            assert ps["handoffs"] == len(PROMPTS)
            assert ps["handoff_s"] > 0
            # prefill tier never decodes: zero blocks, zero tokens
            assert ps["block_syncs"] == 0 and ps["tokens"] == 0
            # no token events ever left the prefill tier
            assert not fallback_events

            # Tier 2: adopt every frame, then run the SAME prompts.
            for i in range(len(PROMPTS)):
                h = decode_kv_handoff(frames[f"r{i}"])
                if h.p:
                    assert eng_d.adopt_prefix(h)
            got = drive(sched_d, PROMPTS)
            assert [g[0] for g in got] == [r[0] for r in reference], \
                "greedy disagg text diverged from unified"
            assert [g[1] for g in got] == [r[1] for r in reference]

            ds = sched_d.stats()
            assert ds["role"] == "decode"
            # Satellite contract: a decode-role host books adoption
            # dispatches, NOT unified-mode admission prefill — the only
            # admit dispatch allowed is the p=0 routing-only prompt's
            # full prefill (which IS admission work, on any tier).
            assert ds["adopt_dispatches"] >= 2  # p=16 unit + p=24 seed
            assert ds["admit_dispatches"] == 1  # the routing-only prompt
            assert ds["adopt_s"] > 0
            assert "adopt_dispatch_s" in ds
        finally:
            sched_p.stop()
            sched_d.stop()

    def test_budget_rejected_adoption_still_token_identical(self, setup,
                                                            reference):
        """A decode tier whose store cannot hold the entry falls back to
        a full prefill — slower, but the stream must be byte-identical."""
        cfg, params = setup
        eng_d = make_engine(cfg, params, role="decode", cache_mb=1e-4)
        # Decode-role construction raises an undersized budget to the
        # geometry floor (2 × largest-bucket prefix worth of blocks) —
        # a default too small for the model must never silently reject
        # EVERY adoption.
        assert eng_d.block_pool.budget_bytes >= \
            2 * 32 * eng_d.kv_bytes_per_token()
        # Simulate a pool with no headroom: allocate every block OUTSIDE
        # the tree (nothing is evictable), so plan_insert rejects,
        # lookup misses, and admission runs the ordinary full-prefill
        # path.
        eng_d.block_pool.alloc(eng_d.block_pool.free_count)
        eng_d.warmup()
        h = decode_kv_handoff(encode_kv_handoff(
            "r0", PROMPTS[0], 16,
            gqa_arrays(L=cfg.num_layers, K=cfg.num_kv_heads,
                       D=cfg.dim_per_head, p=16)))
        # NOTE: arrays here are random, NOT the true prefix KV — the
        # rejection path must not adopt them, which the identity check
        # below proves (adopted garbage would change the text).
        assert eng_d.adopt_prefix(h) is False
        sched = Scheduler(eng_d)
        sched.start()
        try:
            got = drive(sched, [PROMPTS[0]])
            assert got[0][0] == reference[0][0]
        finally:
            sched.stop()


# ---------------------------------------------------------------------
# Process-level identity: the same contract through REAL engine hosts
# (unified single host vs disagg pair), greedy, over the host pipes.


@pytest.mark.slow
class TestBackendDisaggIdentity:
    @staticmethod
    def _cfg(role, disagg_net=None, tpu_extra=None):
        from symmetry_tpu.provider.config import ConfigManager

        return ConfigManager(config={
            "name": "disagg-id", "public": False, "serverKey": "00" * 32,
            "modelName": "tiny:test", "apiProvider": "tpu_native",
            "dataCollectionEnabled": False,
            "tpu": {"model_preset": "tiny", "dtype": "float32",
                    "max_batch_size": 4, "max_seq_len": 128,
                    "prefill_buckets": [32, 64], "prefill_chunk": 16,
                    "engine_isolation": "process", "role": role,
                    **(tpu_extra or {}),
                    **({"disagg": disagg_net} if disagg_net else {})},
        })

    CONTENTS = ["tell me about disagg serving",  # multi-chunk prefix
                "hi"]  # minimal prompt (template still spans align)

    @classmethod
    def _collect_all(cls, role, disagg_net=None):
        import asyncio

        from symmetry_tpu.provider.backends.base import InferenceRequest
        from symmetry_tpu.provider.backends.tpu_native import (
            TpuNativeBackend)

        async def go():
            backend = TpuNativeBackend(cls._cfg(role, disagg_net))
            await backend.start()
            try:
                out = []
                for content in cls.CONTENTS:
                    text = []
                    async for chunk in backend.stream(InferenceRequest(
                            messages=[{"role": "user",
                                       "content": content}],
                            max_tokens=8, temperature=0.0)):
                        if chunk.text:
                            text.append(chunk.text)
                    out.append("".join(text))
                stats = await backend.engine_stats()
                return out, stats
            finally:
                await backend.stop()

        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(go(), 600))

    def test_process_mode_greedy_identity(self):
        unified, _ = self._collect_all("unified")
        disagg, stats = self._collect_all("disagg")
        assert disagg == unified, \
            "greedy disagg diverged from unified through real host pipes"
        dg = stats.get("disagg") or {}
        assert dg.get("handoff_frames") == 2
        # The chat template alone spans the 16-token alignment, so even
        # "hi" ships real KV (routing-only is covered at the host layer
        # in TestHostWireOps).
        assert dg.get("routing_only") == 0
        assert dg.get("handoff_bytes", 0) > 0
        assert (dg.get("prefill_host") or {}).get("role") == "prefill"

    def test_pool_1x1_memory_greedy_identity(self):
        """Acceptance pin: a pool of 1×1 is behaviorally identical to
        the pair — greedy output matches unified token-for-token and
        the handoff ledger carries both requests, with zero churn."""
        unified, _ = self._collect_all("unified")
        pooled, stats = self._collect_all(
            "disagg", disagg_net={"peer": "mem://pool-id-1x1",
                                  "pool": {"prefill": 1, "decode": 1}})
        assert pooled == unified, \
            "greedy 1×1 pool diverged from unified"
        dg = stats.get("disagg") or {}
        assert dg.get("handoff_frames") == 2
        pb = dg.get("pool") or {}
        assert pb["healthy"] == {"prefill": 1, "decode": 1}
        assert pb["re_placements"] == 0 and pb["losses"] == 0
        assert pb["members"]["prefill-0"]["placements"] == 2
        assert pb["members"]["decode-0"]["placements"] == 2

    def test_pool_2x2_memory_greedy_identity(self):
        """Acceptance pin: greedy decode is token-identical between a
        2×2 memory-transport pool and unified — adoption through ANY
        member must not change a single token — and sequential
        least-loaded placement spreads work across every member."""
        unified, _ = self._collect_all("unified")
        pooled, stats = self._collect_all(
            "disagg", disagg_net={"peer": "mem://pool-id-2x2",
                                  "pool": {"prefill": 2, "decode": 2}})
        assert pooled == unified, \
            "greedy 2×2 pool diverged from unified"
        dg = stats.get("disagg") or {}
        assert dg.get("handoff_frames") == 2
        pb = dg.get("pool") or {}
        assert pb["healthy"] == {"prefill": 2, "decode": 2}
        assert pb["re_placements"] == 0 and pb["losses"] == 0
        # two sequential requests, four members: each tier spread one
        # request per member (lifetime placements break the idle tie)
        for member_id in ("prefill-0", "prefill-1",
                          "decode-0", "decode-1"):
            assert pb["members"][member_id]["placements"] == 1, pb

    # A two-turn session: turn 2 extends turn 1, so after gossip the
    # prefill member that served turn 1 advertises the shared prefix.
    SESSION = ["tell me about disagg serving",
               "tell me about disagg serving and why it helps"]

    @classmethod
    def _collect_session(cls, role, disagg_net=None, tpu_extra=None,
                         settle_s=0.0):
        import asyncio

        from symmetry_tpu.provider.backends.base import InferenceRequest
        from symmetry_tpu.provider.backends.tpu_native import (
            TpuNativeBackend)

        async def go():
            backend = TpuNativeBackend(
                cls._cfg(role, disagg_net, tpu_extra))
            await backend.start()
            try:
                out = []
                for content in cls.SESSION:
                    text = []
                    async for chunk in backend.stream(InferenceRequest(
                            messages=[{"role": "user",
                                       "content": content}],
                            max_tokens=8, temperature=0.0)):
                        if chunk.text:
                            text.append(chunk.text)
                    out.append("".join(text))
                    if settle_s:
                        # let the heartbeat carry the gossip rider
                        await asyncio.sleep(settle_s)
                stats = await backend.engine_stats()
                return out, stats
            finally:
                await backend.stop()

        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(go(), 600))

    def test_pool_2x2_affinity_token_identity(self):
        """Affinity changes PLACEMENT, never tokens: the same two-turn
        greedy session through a 2×2 pool is token-identical to
        unified whether cache-affine routing is on or off — and with
        it on, turn 2 is provably routed by predicted hit while the
        weight-0 control stays load-only."""
        unified, _ = self._collect_session("unified")
        # settle 1s between turns in BOTH pool runs so the only
        # difference is the affinity weight, not gossip timing
        on, stats_on = self._collect_session(
            "disagg",
            disagg_net={"peer": "mem://pool-affinity-on",
                        "pool": {"prefill": 2, "decode": 2,
                                 "heartbeat_s": 0.3}},
            tpu_extra={"prefix_gossip_s": 0.1,
                       "pool_affinity_weight": 1.0},
            settle_s=1.0)
        off, stats_off = self._collect_session(
            "disagg",
            disagg_net={"peer": "mem://pool-affinity-off",
                        "pool": {"prefill": 2, "decode": 2,
                                 "heartbeat_s": 0.3}},
            tpu_extra={"prefix_gossip_s": 0.1,
                       "pool_affinity_weight": 0.0},
            settle_s=1.0)
        assert on == unified, \
            "greedy session with affinity routing diverged from unified"
        assert off == unified, \
            "greedy session with affinity disabled diverged from unified"
        pool_on = (stats_on.get("disagg") or {}).get("pool") or {}
        pool_off = (stats_off.get("disagg") or {}).get("pool") or {}
        assert pool_on.get("affinity_hit", 0) >= 1, pool_on
        warm = [mid for mid, m in (pool_on.get("members") or {}).items()
                if m.get("hit_blocks", 0) > 0]
        assert warm, pool_on
        assert pool_off.get("affinity_hit", 0) == 0, pool_off
        assert pool_off.get("affinity_load_only", 0) >= 1, pool_off

    def test_network_mode_tcp_greedy_identity(self):
        """THE cross-machine acceptance contract: both tiers as real
        engine hosts connected ONLY through the TCP handoff link
        (chunked, credit-gated, acked) — greedy output must be
        token-identical to unified, and the wire-split stats must be
        populated."""
        unified, _ = self._collect_all("unified")
        disagg, stats = self._collect_all(
            "disagg", disagg_net={"peer": "tcp://127.0.0.1:0",
                                  "inline": True, "chunk_kb": 4})
        assert disagg == unified, \
            "greedy disagg-over-TCP diverged from unified"
        dg = stats.get("disagg") or {}
        assert dg.get("handoff_frames") == 2
        assert dg.get("wire_frames") == 2
        assert (dg.get("wire_s") or {}).get("count") == 2
        assert dg.get("handoff_bytes", 0) > 0
        assert (dg.get("prefill_host") or {}).get("role") == "prefill"
        link = dg.get("link") or {}
        assert link.get("connected") is True
        assert link.get("partial_discards") == 0
        node = dg.get("node") or {}
        assert node.get("handoffs_sent") == 2
        assert node.get("retries") == 0


# ---------------------------------------------------------------------
# Host wire ops (no subprocess: EngineHost methods against stub engines)


class _StubPrefillEngine:
    prefix_align = 8
    prefix_block = 8
    kv_quant = False

    def __init__(self, cfg, params):
        self._real = None  # unused; extract served from canned arrays
        self.calls = []

    def kv_bytes_per_token(self):
        return 2 * 2 * 2 * 4 * 4  # 2 planes × L2 × K2 × D4 × f32

    def extract_slot_kv(self, slot, p):
        import jax.numpy as jnp

        from symmetry_tpu.models.llama import KVCache

        self.calls.append((slot, p))
        return KVCache(k=jnp.zeros((2, 1, 32, 2, 4), jnp.float32),
                       v=jnp.zeros((2, 1, 32, 2, 4), jnp.float32),
                       lengths=jnp.full((1,), p, jnp.int32))


class TestHostWireOps:
    def _host(self, role):
        from symmetry_tpu.engine.host import EngineHost

        host = EngineHost(config=None)
        host._role = role
        return host

    def test_handoff_sink_emits_frame(self, setup, capsys):
        host = self._host("prefill")
        host._engine = _StubPrefillEngine(*setup)
        req = GenRequest(prompt_ids=list(range(20)),
                         sampling=SamplingParams(), max_new_tokens=4,
                         emit=lambda ev: None, id="h1")
        host._reported["h1"] = 0
        host._handoff_sink(2, req, 99)
        line = json.loads(capsys.readouterr().out.strip())
        assert line["op"] == "handoff" and line["id"] == "h1"
        assert line["p"] == 16 and line["prompt_len"] == 20
        import base64

        h = decode_kv_handoff(base64.b64decode(line["frame"]))
        assert h.p == 16 and h.arrays["k"].shape == (2, 1, 16, 2, 4)
        assert line["nbytes"] == len(base64.b64decode(line["frame"]))
        assert host.handoff_stats["frames"] == 1
        assert host.handoff_stats["prefix_tokens"] == 16
        assert "h1" not in host._reported  # ownership moved tiers
        assert host._engine.calls == [(2, 16)]

    def test_routing_only_fast_path_no_extract(self, setup, capsys):
        host = self._host("prefill")
        host._engine = _StubPrefillEngine(*setup)
        host._emit_handoff("h2", [1, 2, 3], 0, None)
        line = json.loads(capsys.readouterr().out.strip())
        assert line["p"] == 0
        assert host._engine.calls == []  # no device work for p=0
        assert host.handoff_stats["routing_only"] == 1

    def _submitting_host(self):
        host = self._host("decode")
        submits = []
        host._scheduler = type("S", (), {
            "submit": lambda self, req: submits.append(req)})()
        return host, submits

    def test_adopt_defers_frame_work_to_engine_thread_thunk(self, capsys):
        """The adopt op submits WITHOUT parsing the frame (the serial
        command loop must never pay for a multi-hundred-MB decode); the
        thunk — run by the scheduler on the engine thread — parses,
        fills prompt_ids, and adopts."""
        import base64

        host, submits = self._submitting_host()
        adopted = []
        host._engine = type("E", (), {
            "adopt_prefix": lambda self, h: adopted.append(h.p) or True})()
        tokens = list(range(20))
        frame = encode_kv_handoff("r8", tokens, 16, gqa_arrays())
        host._handle_adopt({"op": "adopt", "id": "r8",
                            "frame": base64.b64encode(frame).decode(),
                            "max_new": 4})
        assert len(submits) == 1
        req = submits[0]
        assert req.prompt_ids == []  # frame not parsed yet
        assert host.adopt_stats["frames"] == 0
        req.adopt(req)
        assert req.prompt_ids == tokens  # thunk filled it
        assert adopted == [16]
        assert host.adopt_stats["frames"] == 1
        assert host.adopt_stats["adopted"] == 1
        assert host.adopt_stats["bytes"] == len(frame)

    def test_adopt_corrupt_frame_fails_in_thunk(self, capsys):
        import base64

        host, submits = self._submitting_host()
        bad = bytearray(encode_kv_handoff("r9", list(range(20)), 16,
                                          gqa_arrays()))
        bad[60] ^= 0xFF
        host._handle_adopt({"op": "adopt", "id": "r9",
                            "frame": base64.b64encode(bytes(bad)).decode(),
                            "max_new": 4})
        assert len(submits) == 1
        with pytest.raises(RuntimeError, match="adoption failed"):
            submits[0].adopt(submits[0])
        assert host.adopt_stats["errors"] == 1
        assert host.adopt_stats["frames"] == 0  # nothing adopted

    def test_adopt_id_mismatch_fails_in_thunk(self):
        import base64

        host, submits = self._submitting_host()
        frame = encode_kv_handoff("other", [1, 2, 3], 0, None)
        host._handle_adopt({"op": "adopt", "id": "mine",
                            "frame": base64.b64encode(frame).decode()})
        with pytest.raises(RuntimeError, match="adoption failed"):
            submits[0].adopt(submits[0])
        assert host.adopt_stats["errors"] == 1

    def test_adopt_missing_frame_is_immediate_error_event(self, capsys):
        host, submits = self._submitting_host()
        host._handle_adopt({"op": "adopt", "id": "r10", "max_new": 4})
        line = json.loads(capsys.readouterr().out.strip())
        assert line["finish_reason"] == "error"
        assert "no frame" in line["error"]
        assert submits == []
        assert host.adopt_stats["errors"] == 1


# ---------------------------------------------------------------------
# Cross-machine handoff link (engine/disagg/net.py)


from symmetry_tpu.engine.disagg.net import (  # noqa: E402
    CreditGate,
    HandoffLink,
    LinkConfig,
    LinkDecoder,
    LinkError,
    PrefillLink,
    Reassembler,
    encode_link_msg,
    link_clock_handshake,
)
from symmetry_tpu.protocol.keys import HOST_OPS, LINK_OPS, LinkOp  # noqa: E402
from symmetry_tpu.transport.base import Connection  # noqa: E402
from symmetry_tpu.transport.memory import memory_pair  # noqa: E402
from symmetry_tpu.utils.faults import FAULTS  # noqa: E402


def run_async(coro, timeout=60):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


class _RechunkConnection(Connection):
    """Proxy that deliberately violates every frame boundary: inbound
    bytes are re-sliced at seeded-random offsets (fragmenting AND
    coalescing), which is exactly what the link's streaming envelope
    decoder must survive."""

    def __init__(self, inner, seed=0):
        self._inner = inner
        self._rng = random.Random(seed)
        self._buf = bytearray()
        self._eof = False

    async def send(self, frame):
        await self._inner.send(frame)

    async def recv(self):
        while not self._buf:
            if self._eof:
                return None
            f = await self._inner.recv()
            if f is None:
                self._eof = True
                break
            self._buf += f
        if not self._buf:
            return None
        n = self._rng.randint(1, min(len(self._buf), 97))
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    async def close(self):
        await self._inner.close()

    @property
    def closed(self):
        return self._inner.closed


class _ManglingConnection(Connection):
    """Proxy that flips the LAST byte of the Nth outbound frame — for a
    link `chunk` message that byte is frame payload, so the transfer's
    CRC check must catch it and nak."""

    def __init__(self, inner, mangle_frame):
        self._inner = inner
        self._mangle_frame = mangle_frame
        self._n = 0

    async def send(self, frame):
        self._n += 1
        if self._n == self._mangle_frame:
            frame = frame[:-1] + bytes([frame[-1] ^ 0xFF])
        await self._inner.send(frame)

    async def recv(self):
        return await self._inner.recv()

    async def close(self):
        await self._inner.close()

    @property
    def closed(self):
        return self._inner.closed


class TestLinkEnvelope:
    def test_roundtrip_under_arbitrary_fragmentation(self):
        rng = random.Random(11)
        msgs = [({"op": "chunk", "seq": i},
                 rng.randbytes(rng.randint(0, 4096)))
                for i in range(32)]
        blob = b"".join(encode_link_msg(h, p) for h, p in msgs)
        for seed in range(3):
            r = random.Random(seed)
            dec = LinkDecoder()
            out = []
            i = 0
            while i < len(blob):
                n = r.randint(1, 513)
                out.extend(dec.feed(blob[i:i + n]))
                i += n
            assert [(h["seq"], p) for h, p in out] \
                == [(h["seq"], p) for h, p in msgs]

    def test_bad_magic_rejected(self):
        dec = LinkDecoder()
        with pytest.raises(LinkError, match="magic"):
            list(dec.feed(b"XXXX" + b"\x00" * 12))

    def test_oversized_header_rejected(self):
        bad = struct.pack("<4sII", b"SYLK", 1 << 24, 0)
        with pytest.raises(LinkError, match="too large"):
            list(LinkDecoder().feed(bad))

    def test_registry_pins_link_ops(self):
        # Every wire op the link protocol speaks is registered — the
        # wire-contract checker pivots on this set (no raw literals
        # outside tests), and the deliberate HostOp value reuse (a link
        # `submit` forwards a host `submit`) is pinned as intentional.
        # ping/pong/drain/leave are the pool-membership extensions
        # (keepalive + deliberate-churn announces).
        assert LINK_OPS == {"hello", "clock", "submit", "cancel",
                            "stats", "trace", "credit", "ack", "nak",
                            "begin", "chunk", "end", "fail", "event",
                            "ping", "pong", "drain", "leave"}
        assert LINK_OPS & HOST_OPS == {"clock", "submit", "cancel",
                                       "stats", "trace", "event"}


class _MiniDecodePump:
    """The decode side of the bulk path, driven manually: the REAL
    DecodeLink pump internals (Reassembler + credit grants + ack/nak)
    without the dial loop, so each test controls the link lifetime."""

    def __init__(self, conn, *, ack=True):
        self.link = HandoffLink(conn)
        self.reasm = Reassembler()
        self.got = []
        self.fails = []
        self.ack = ack

    async def run(self):
        while True:
            msg = await self.link.recv()
            if msg is None:
                return
            header, payload = msg
            op = header.get("op")
            try:
                if op == LinkOp.CHUNK:
                    await self.link.send({"op": LinkOp.CREDIT,
                                          "n": len(payload)})
                    self.reasm.chunk(header, payload)
                elif op == LinkOp.BEGIN:
                    self.reasm.begin(header)
                elif op == LinkOp.END:
                    meta, frame = self.reasm.end(header)
                    if self.ack:
                        self.got.append((meta, frame))
                        await self.link.send(
                            {"op": LinkOp.ACK,
                             "xfer": header.get("xfer")})
                elif op == LinkOp.FAIL:
                    self.fails.append(header)
            except LinkError as exc:
                if self.link.closed or "send failed" in str(exc):
                    return  # peer reset the link mid-message
                await self.link.send({"op": LinkOp.NAK,
                                      "xfer": header.get("xfer")})


def _plink(conn, **cfg_overrides):
    cfg = LinkConfig({"chunk_kb": 1, **cfg_overrides})
    return PrefillLink(HandoffLink(conn), cfg,
                       on_command=lambda line: None,
                       on_probe=lambda op: None)


class TestLinkTransfer:
    FRAME = encode_kv_handoff("w1", list(range(40)), 32,
                              gqa_arrays(p=32))

    def test_multi_chunk_reassembly_over_fragmenting_transport(self):
        async def main():
            a, b = memory_pair()
            pump = _MiniDecodePump(_RechunkConnection(a, seed=3))
            plink = _plink(b)
            t1 = asyncio.ensure_future(pump.run())
            t2 = asyncio.ensure_future(plink.serve())
            ok = await plink.send_handoff(
                {"id": "w1", "p": 32, "nbytes": len(self.FRAME)},
                self.FRAME)
            assert ok
            assert len(pump.got) == 1
            meta, frame = pump.got[0]
            assert frame == self.FRAME  # byte-identical after rechunking
            assert meta["id"] == "w1" and meta["len"] == len(self.FRAME)
            # ...and the reassembled bytes still parse as a valid KV
            # frame (the corruption suite's contract, now on the wire).
            h = decode_kv_handoff(frame)
            assert h.p == 32 and h.request_id == "w1"
            assert len(self.FRAME) > 1024  # genuinely multi-chunk
            t1.cancel()
            t2.cancel()

        run_async(main())

    def test_corrupt_chunk_naks_then_retransmit_succeeds(self):
        async def main():
            a, b = memory_pair()
            pump = _MiniDecodePump(a)
            # Frame #2 on the wire is attempt 1's first chunk (after
            # begin); its last byte is chunk payload → CRC mismatch at
            # end → nak → attempt 2 retransmits clean.
            plink = _plink(_ManglingConnection(b, mangle_frame=2))
            t1 = asyncio.ensure_future(pump.run())
            t2 = asyncio.ensure_future(plink.serve())
            ok = await plink.send_handoff(
                {"id": "w2", "p": 32, "nbytes": len(self.FRAME)},
                self.FRAME)
            assert ok
            assert plink.sender.stats["retries"] == 1
            assert len(pump.got) == 1 and pump.got[0][1] == self.FRAME
            # the corrupt attempt was discarded whole, never surfaced
            assert pump.reasm.stats["partial_discards"] == 1
            t1.cancel()
            t2.cancel()

        run_async(main())

    def test_mid_transfer_disconnect_discards_partial(self):
        async def main():
            FAULTS.load({"disagg.net.drop_link": "drop_frame@once"})
            try:
                a, b = memory_pair()
                pump = _MiniDecodePump(a)
                plink = _plink(b)
                t1 = asyncio.ensure_future(pump.run())
                t2 = asyncio.ensure_future(plink.serve())
                ok = await plink.send_handoff(
                    {"id": "w3", "p": 32, "nbytes": len(self.FRAME)},
                    self.FRAME)
                assert not ok  # the cable was pulled mid-transfer
                await asyncio.wait_for(t1, 5)  # pump sees EOF and exits
                # ZERO partial adoptions: nothing reached the handoff
                # callback, and the partial buffer is discarded whole.
                assert pump.got == []
                assert pump.reasm.active == 1
                assert pump.reasm.abort_all() == 1
                assert pump.reasm.active == 0
                t2.cancel()
            finally:
                FAULTS.clear()

        run_async(main())

    def test_credit_window_backpressures_sender(self):
        async def main():
            a, b = memory_pair()
            pump = _MiniDecodePump(a)
            # Window of ~one chunk: every subsequent chunk must wait
            # for the receiver's credit grant — the stall that, in the
            # real topology, propagates into prefill admissions.
            plink = _plink(b, credit_mb=1024 / 2**20)
            t1 = asyncio.ensure_future(pump.run())
            t2 = asyncio.ensure_future(plink.serve())
            ok = await plink.send_handoff(
                {"id": "w4", "p": 32, "nbytes": len(self.FRAME)},
                self.FRAME)
            assert ok
            stats = plink.stats()
            assert stats["credit_stalls"] > 0
            assert stats["credit_stall_s"] >= 0
            t1.cancel()
            t2.cancel()

        run_async(main())

    def test_ack_timeout_retries_then_fails(self):
        async def main():
            a, b = memory_pair()
            pump = _MiniDecodePump(a, ack=False)  # reassembles, never acks
            plink = _plink(b, ack_timeout_s=0.2, max_retries=1)
            t1 = asyncio.ensure_future(pump.run())
            t2 = asyncio.ensure_future(plink.serve())
            ok = await plink.send_handoff(
                {"id": "w5", "p": 32, "nbytes": len(self.FRAME)},
                self.FRAME)
            assert not ok
            assert plink.sender.stats["retries"] == 1
            assert plink.sender.stats["failed"] == 1
            await asyncio.sleep(0.05)
            assert pump.fails and pump.fails[0]["id"] == "w5"
            t1.cancel()
            t2.cancel()

        run_async(main())

    def test_clock_handshake_measures_skew(self):
        async def main():
            a, b = memory_pair()
            dialer = HandoffLink(a)
            responder = HandoffLink(b)

            async def echo_skewed():
                while True:
                    msg = await responder.recv()
                    if msg is None:
                        return
                    h, _ = msg
                    if h.get("op") == LinkOp.CLOCK:
                        await responder.send(
                            {"op": LinkOp.CLOCK, "t0": h.get("t0"),
                             "t": time.monotonic() + 5.0})

            t = asyncio.ensure_future(echo_skewed())
            offset = await link_clock_handshake(dialer)
            assert 4.9 < offset < 5.1  # the deliberate +5s skew, found
            t.cancel()

        run_async(main())

    def test_send_recv_fault_seams(self):
        async def main():
            a, b = memory_pair()
            tx = HandoffLink(b)
            rx = HandoffLink(a)
            # egress drop: the armed message vanishes on the wire
            FAULTS.load({"disagg.net.send": "drop_frame@once"})
            try:
                await tx.send({"op": LinkOp.CREDIT, "n": 1})  # dropped
                await tx.send({"op": LinkOp.CREDIT, "n": 2})
                h, _ = await rx.recv()
                assert h["n"] == 2
            finally:
                FAULTS.clear()
            # ingress drop: delivered bytes, message discarded on recv
            FAULTS.load({"disagg.net.recv": "drop_frame@once"})
            try:
                await tx.send({"op": LinkOp.CREDIT, "n": 3})  # discarded
                await tx.send({"op": LinkOp.CREDIT, "n": 4})
                h, _ = await rx.recv()
                assert h["n"] == 4
            finally:
                FAULTS.clear()

        run_async(main())


# ---------------------------------------------------------------------
# Elastic pool: router unit suite (pure state — no sockets, no procs)


from symmetry_tpu.engine.disagg.pool import (  # noqa: E402
    MemberState,
    PoolConfig,
    PoolRouter,
)


def healthy_pool(m_prefill=2, n_decode=2):
    r = PoolRouter()
    for i in range(m_prefill):
        r.add_member(f"p{i}", "prefill")
        r.mark_healthy(f"p{i}")
    for i in range(n_decode):
        r.add_member(f"d{i}", "decode")
        r.mark_healthy(f"d{i}")
    return r


class TestPoolRouter:
    def test_least_loaded_placement(self):
        r = healthy_pool()
        a = r.place("r1")
        b = r.place("r2")
        assert {a, b} == {"p0", "p1"}  # spread, not pile-up
        # p0 and p1 each hold one; a third goes wherever load frees
        r.note_done("r1")
        assert r.place("r3") == a  # the emptied member wins

    def test_queue_depth_gauge_steers_placement(self):
        r = healthy_pool()
        r.update_gauges("p0", queue_depth=5)
        assert r.place("r1") == "p1"
        r.update_gauges("p1", queue_depth=9)
        assert r.place("r2") == "p0"  # 5+0 beats 9+1

    def test_burn_rate_breaks_ties(self):
        r = healthy_pool()
        r.update_gauges("p0", burn_rate=2.0)
        assert r.place("r1") == "p1"  # equal load, p0 burning budget

    def test_route_decode_releases_prefill_and_balances(self):
        r = healthy_pool()
        p = r.place("r1")
        d1 = r.route_decode("r1")
        assert r.assigned_to("r1") is None  # migration left the tier
        assert r.get(p).in_flight == set()
        r.place("r2")
        d2 = r.route_decode("r2")
        assert {d1, d2} == {"d0", "d1"}

    def test_drain_excludes_new_but_keeps_in_flight(self):
        r = healthy_pool()
        first = r.place("r1")
        r.drain(first)
        assert r.get(first).state == MemberState.DRAINING
        # in-flight work stays on the draining member...
        assert "r1" in r.get(first).in_flight
        # ...but every new placement avoids it
        for i in range(4):
            assert r.place(f"n{i}") != first
        assert r.counters["drains"] == 1
        # completion drains it naturally
        r.note_done("r1")
        assert r.get(first).in_flight == set()

    def test_dead_node_re_placement(self):
        r = healthy_pool()
        victim = r.place("r1")
        r.place("r2")  # lands on the other member
        ids = r.on_lost(victim)
        assert ids == ["r1"]
        assert r.get(victim).state == MemberState.LOST
        survivor = r.place("r1")
        assert survivor is not None and survivor != victim
        r.record_placement("r1", replacement=True)
        assert r.counters["re_placements"] == 1
        assert r.counters["losses"] == 1
        # second loss signal is idempotent — no double-shed
        assert r.on_lost(victim) == []

    def test_hot_join_and_rejoin(self):
        r = healthy_pool(m_prefill=1)
        lost = r.place("r1")
        r.on_lost(lost)
        assert r.place("r2") is None  # no survivor: caller sheds
        # hot-join: a brand-new member becomes placeable immediately
        r.add_member("p9", "prefill")
        r.mark_healthy("p9")
        assert r.place("r2") == "p9"
        # rejoin: the lost member reconnects and serves again
        r.mark_healthy(lost, node_id="node-a")
        assert r.counters["rejoins"] == 1
        assert r.get(lost).node_id == "node-a"
        assert r.place("r3") == lost  # least-loaded again

    def test_pool_of_one_degenerates_to_pair_semantics(self):
        r = healthy_pool(m_prefill=1, n_decode=1)
        # the single member takes every placement while healthy
        assert [r.place(f"r{i}") for i in range(3)] == ["p0"] * 3
        assert all(r.route_decode(f"r{i}") == "d0" for i in range(3))
        # its loss leaves nothing to re-place onto — the caller sheds
        # structured-retryable, exactly the pair's link-down behavior
        ids = r.on_lost("d0")
        assert sorted(ids) == ["r0", "r1", "r2"]
        assert r.place("r9") == "p0"  # prefill tier untouched
        assert r.route_decode("r9") is None

    def test_drain_refuses_last_healthy_member_of_tier(self):
        """Draining the LAST healthy member of a tier would leave it
        empty with no fault in sight — the router refuses and the
        caller (autoscaler, operator) must grow first."""
        r = healthy_pool(m_prefill=2, n_decode=1)
        assert r.drain("d0") is False  # sole decode member: refused
        assert r.get("d0").state is MemberState.HEALTHY
        assert r.counters["drain_refused"] == 1
        assert r.drain("p0") is True  # prefill has a survivor
        assert r.drain("p0") is True  # idempotent on a draining member
        assert r.drain("p1") is False  # p0 draining → p1 is now last
        r.add_member("p9", "prefill")
        r.mark_healthy("p9")
        assert r.drain("p1") is True  # replacement arrived: allowed

    def test_exclude_walks_past_refusing_members(self):
        r = healthy_pool(m_prefill=3)
        got = set()
        exclude = set()
        for _ in range(3):
            m = r.place("r1", exclude=exclude)
            got.add(m)
            r.release("r1")
            exclude.add(m)
        assert got == {"p0", "p1", "p2"}
        assert r.place("r1", exclude=exclude) is None

    def test_release_undoes_unsent_placement(self):
        r = healthy_pool(m_prefill=1)
        r.place("r1")
        r.release("r1")
        assert r.get("p0").in_flight == set()
        assert r.assigned_to("r1") is None
        # an unconfirmed placement never reaches the ledger — refused
        # sends must not inflate SHARE or skew the round-robin
        assert r.get("p0").placements == 0
        assert r.counters["placements"] == 0
        r.place("r1")
        r.record_placement("r1")
        assert r.get("p0").placements == 1
        assert r.counters["placements"] == 1

    def test_joining_and_lost_members_never_placed(self):
        r = PoolRouter()
        r.add_member("p0", "prefill")  # joining — not yet serving
        assert r.place("r1") is None
        r.mark_healthy("p0")
        assert r.place("r1") == "p0"

    def test_stats_shape(self):
        r = healthy_pool()
        r.place("r1")
        st = r.stats()
        assert st["healthy"] == {"prefill": 2, "decode": 2}
        assert st["in_flight"] == {"prefill": 1, "decode": 0}
        assert set(st["members"]) == {"p0", "p1", "d0", "d1"}
        m = st["members"]["p0"]
        assert {"tier", "state", "in_flight", "placements",
                "queue_depth"} <= set(m)


def _affinity_router(t, *, m_prefill=2, n_decode=2, heartbeat_s=1.0,
                     weight=1.0):
    """healthy_pool with an injectable clock (`t` is a one-element
    list) so staleness decay and gauge-age tests control time."""
    r = PoolRouter(heartbeat_s=heartbeat_s, affinity_weight=weight,
                   clock=lambda: t[0])
    for i in range(m_prefill):
        r.add_member(f"p{i}", "prefill")
        r.mark_healthy(f"p{i}")
    for i in range(n_decode):
        r.add_member(f"d{i}", "decode")
        r.mark_healthy(f"d{i}")
    return r


def _blocks(n, bs=16, base=0):
    from symmetry_tpu.engine.prefix_cache import block_digests

    return block_digests([base + i for i in range(n * bs)], n * bs, bs)


class TestPoolAffinity:
    def test_predicted_hit_outbids_load(self):
        t = [0.0]
        r = _affinity_router(t)
        digests = _blocks(4)
        r.update_gauges("p0", queue_depth=0.0)
        r.update_gauges("p1", queue_depth=3.0)
        r.update_summary("p1", {"block_tokens": 16, "digests": digests})
        # p1 carries 3 queue slots but a fresh 4-block predicted hit —
        # at weight 1 the warm member wins.
        assert r.place("s1", digests=digests) == "p1"
        assert r.counters["affinity_hit"] == 1
        assert r.get("p1").hit_blocks == 4
        # no digests → pure load (p0 is empty)
        assert r.place("s2") == "p0"
        assert r.counters["affinity_load_only"] == 1

    def test_weight_zero_restores_load_only(self):
        t = [0.0]
        r = _affinity_router(t, weight=0.0)
        digests = _blocks(4)
        r.update_gauges("p1", queue_depth=3.0)
        r.update_summary("p1", {"block_tokens": 16, "digests": digests})
        assert r.place("s1", digests=digests) == "p0"
        assert r.counters["affinity_load_only"] == 1
        assert r.counters["affinity_hit"] == 0

    def test_hit_must_be_contiguous_from_block_zero(self):
        t = [0.0]
        r = _affinity_router(t)
        digests = _blocks(4)
        r.update_gauges("p1", queue_depth=0.0)
        # p1 holds only the TAIL blocks: digest 0 is missing, so the
        # radix tree can serve none of it — predicted hit 0, cold.
        r.update_summary("p1", {"block_tokens": 16,
                                "digests": digests[1:]})
        assert r.predicted_hit(r.get("p1"), digests) == 0
        r.place("s1", digests=digests)
        assert r.counters["affinity_cold"] == 1

    def test_summary_staleness_decays_to_load_only(self):
        t = [0.0]
        r = _affinity_router(t, heartbeat_s=1.0)
        digests = _blocks(2)
        r.update_gauges("p0", queue_depth=1.0)
        r.update_summary("p0", {"block_tokens": 16, "digests": digests})
        r.update_gauges("p1", queue_depth=0.0)
        # fresh: p0's 2-block hit (decay 1.0) outbids one queue slot
        assert r.place("s1", digests=digests) == "p0"
        r.note_done("s1")
        # summary ages 10 heartbeats (gauges kept fresh): decay
        # 0.5^(10/2) ≈ 0.03 → hit term ~0.06 < 1 queue slot → p1 wins.
        t[0] = 10.0
        r.update_gauges("p0", queue_depth=1.0)
        r.update_gauges("p1", queue_depth=0.0)
        assert r.place("s2", digests=digests) == "p1"

    def test_stale_gauges_exclude_member_from_affinity(self):
        """Satellite-fix pin: a member that stops heartbeating keeps
        its last summary, but once its gauges are older than two
        heartbeat periods the summary describes a cache we can no
        longer see — affinity scoring must ignore it."""
        t = [0.0]
        r = _affinity_router(t, heartbeat_s=1.0)
        digests = _blocks(3)
        r.update_gauges("p0", queue_depth=0.0)
        r.update_summary("p0", {"block_tokens": 16, "digests": digests})
        assert r.predicted_hit(r.get("p0"), digests) == 3
        t[0] = 2.5  # > 2 × heartbeat since the last gauge stamp
        assert r.predicted_hit(r.get("p0"), digests) == 0
        r.place("s1", digests=digests)
        assert r.counters["affinity_hit"] == 0
        assert r.counters["affinity_cold"] == 1

    def test_rejoin_resets_gauges_and_summary(self):
        """Satellite-fix pin: a rejoined member is a NEW process — the
        pre-loss gauges and summary must not be trusted forever. Until
        its first fresh heartbeat it scores load-only."""
        t = [0.0]
        r = _affinity_router(t)
        digests = _blocks(2)
        r.update_gauges("p0", queue_depth=9.0)
        r.update_summary("p0", {"block_tokens": 16, "digests": digests})
        r.on_lost("p0")
        r.mark_healthy("p0")
        m = r.get("p0")
        assert m.summary is None and m.summary_at is None
        assert m.gauges_at is None and m.queue_depth == 0.0
        assert r.predicted_hit(m, digests) == 0

    def test_member_loss_bumps_ledger_epoch_and_drops_summary(self):
        t = [0.0]
        r = _affinity_router(t)
        digests = _blocks(2)
        r.update_gauges("d0", queue_depth=0.0)
        r.update_summary("d0", {"block_tokens": 16, "digests": digests})
        assert r.ledger_epoch("d0") == 0
        r.on_lost("d0")
        assert r.ledger_epoch("d0") == 1
        assert r.get("d0").summary is None
        # idempotent loss: no double bump
        r.on_lost("d0")
        assert r.ledger_epoch("d0") == 1
        # rejoin serves again but the epoch stays advanced — the
        # prefill tier must drop every pre-loss ledger entry.
        r.mark_healthy("d0")
        assert r.ledger_epoch("d0") == 1

    def test_gossip_rider_round_trip(self):
        """RadixIndex.summary() → update_summary → predicted_hit: the
        digests a member's real radix tree gossips are exactly the ones
        a grown session's routing digests match against."""
        from symmetry_tpu.engine.prefix_cache import (
            BlockPool, RadixIndex, block_digests)

        pool = BlockPool(64, 16, 256)
        idx = RadixIndex(pool)
        session = list(range(48))  # 3 whole blocks
        plan = idx.plan_insert(session)
        assert plan is not None
        plan.commit()
        s = idx.summary(64)
        assert s is not None and s["block_tokens"] == 16
        t = [0.0]
        r = _affinity_router(t, m_prefill=2)
        r.update_gauges("p0", queue_depth=0.0)
        r.update_summary("p0", s)
        # the session grown by another turn still matches its cached
        # whole blocks contiguously
        grown = session + list(range(100, 120))
        bs = s["block_tokens"]
        p = (len(grown) // bs) * bs
        req = block_digests(grown, p, bs)
        assert r.predicted_hit(r.get("p0"), req) == 3
        # an unrelated session shares nothing
        other = block_digests(list(range(500, 548)), 48, bs)
        assert r.predicted_hit(r.get("p0"), other) == 0

    def test_summary_cap_and_empty_walks(self):
        from symmetry_tpu.engine.prefix_cache import BlockPool, RadixIndex

        pool = BlockPool(64, 16, 256)
        idx = RadixIndex(pool)
        assert idx.summary(64) is None  # empty tree gossips nothing
        plan = idx.plan_insert(list(range(64)))
        plan.commit()
        assert idx.summary(0) is None  # rider disabled
        s = idx.summary(2)
        assert len(s["digests"]) == 2  # bounded payload

    def test_planned_decode_consumed_and_survives_loss(self):
        t = [0.0]
        r = _affinity_router(t)
        planned = r.plan_decode("s1")
        assert planned in ("d0", "d1")
        assert r.planned_decode("s1") == planned
        r.place("s1")
        # the handoff routes to the member the ledger was keyed for
        assert r.route_decode("s1") == planned
        assert r.planned_decode("s1") is None  # plan consumed
        # a plan whose member dies re-picks the survivor
        planned2 = r.plan_decode("s2")
        r.place("s2")
        r.on_lost(planned2)
        got = r.route_decode("s2")
        assert got is not None and got != planned2

    def test_outstanding_plans_count_as_load(self):
        """Concurrent submits must spread: a plan books load the
        member WILL carry, or every burst would pile onto one member
        by id tie-break."""
        t = [0.0]
        r = _affinity_router(t)
        a = r.plan_decode("s1")
        b = r.plan_decode("s2")
        assert {a, b} == {"d0", "d1"}

    def test_empty_gossip_beat_keeps_old_summary_aging(self):
        t = [0.0]
        r = _affinity_router(t)
        digests = _blocks(2)
        r.update_gauges("p0", queue_depth=0.0)
        r.update_summary("p0", {"block_tokens": 16, "digests": digests})
        # a beat with no rider (old binary / cache empty) must not
        # flap the signal off — the stored summary keeps aging instead
        r.update_summary("p0", None)
        r.update_summary("p0", {"block_tokens": 16, "digests": []})
        assert r.get("p0").summary is not None
        assert r.predicted_hit(r.get("p0"), digests) == 2

    def test_pool_of_one_affinity_is_pair_semantics(self):
        t = [0.0]
        r = _affinity_router(t, m_prefill=1, n_decode=1)
        digests = _blocks(2)
        # no summary yet: placement still works (cold), ledger epoch 0
        assert r.place("s1", digests=digests) == "p0"
        assert r.plan_decode("s2", digests) == "d0"
        assert r.ledger_epoch("d0") == 0
        assert r.counters["affinity_cold"] == 1


class TestPoolConfig:
    def test_absent_means_pair_mode(self):
        assert not PoolConfig(None).enabled
        assert not PoolConfig({}).enabled
        assert not PoolConfig({"peer": "tcp://x:1"}).enabled

    def test_counts(self):
        cfg = PoolConfig({"pool": {"prefill": 3, "decode": 2,
                                   "heartbeat_s": 1.5}})
        assert cfg.enabled and cfg.prefill_count == 3
        assert cfg.decode_count == 2 and cfg.heartbeat_s == 1.5
        assert cfg.prefill_peers is None

    def test_peer_list(self):
        cfg = PoolConfig({"pool": {"prefill": ["tcp://a:1", "tcp://b:2"]}})
        assert cfg.prefill_peers == ["tcp://a:1", "tcp://b:2"]
        assert cfg.prefill_count == 2 and cfg.decode_count == 1

    def test_link_config_for_peer(self):
        base = LinkConfig({"peer": "tcp://x:1", "chunk_kb": 8,
                           "node_id": "me"})
        per = base.for_peer("tcp://y:2", heartbeat_s=2.0)
        assert per.peer == "tcp://y:2"
        assert per.chunk_bytes == base.chunk_bytes
        assert per.heartbeat_s == 2.0 and base.heartbeat_s == 0.0

    def test_member_listen_addr(self):
        from symmetry_tpu.provider.backends.tpu_native import (
            TpuNativeBackend)

        f = TpuNativeBackend._member_listen_addr
        assert f("mem://pool", 1, 3) == "mem://pool-p1"
        assert f("tcp://127.0.0.1:0", 2, 3) == "tcp://127.0.0.1:0"
        assert f("tcp://10.0.0.1:4631", 0, 2) == "tcp://10.0.0.1:0"
        assert f("tcp://10.0.0.1:4631", 0, 1) == "tcp://10.0.0.1:4631"


# ---------------------------------------------------------------------
# Elastic pool through the real backend plumbing, against fake hosts:
# the full placement → link → node → handoff → adopt → stream path plus
# churn drills, in milliseconds (no JAX engine per member).


import os  # noqa: E402
import sys  # noqa: E402
import uuid  # noqa: E402

FAKE_HOST = os.path.join(os.path.dirname(__file__), "fake_host.py")


def _fake_pool_backend(pool, *, peer=None, link_extra=None,
                       token_delay_s=0.15):
    from symmetry_tpu.engine.disagg.node import PrefillNode
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
    from symmetry_tpu.provider.config import ConfigManager

    class FakePoolBackend(TpuNativeBackend):
        def _host_argv(self, cfg_path):
            return [sys.executable, FAKE_HOST, cfg_path]

        def _node_factory(self, config, listen):
            node = PrefillNode(config, listen=listen)
            node._host_argv = lambda p: [sys.executable, FAKE_HOST, p]
            return node

    cfg = ConfigManager(config={
        "name": "pool-fake", "public": False, "serverKey": "00" * 32,
        "modelName": "fake:pool", "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "fakeHost": {"tokenDelayS": token_delay_s},
        "tpu": {"engine_isolation": "process", "max_batch_size": 4,
                "role": "disagg",
                "supervisor": {"heartbeat_s": 30.0, "wedge_timeout_s": 5.0,
                               "backoff_base_s": 0.05, "backoff_max_s": 0.2,
                               "max_respawns": 2, "spawn_timeout_s": 15.0,
                               "stop_grace_s": 0.5, "min_stable_s": 0.2},
                "disagg": {"peer": peer or f"mem://pool-{uuid.uuid4().hex[:8]}",
                           "reconnect_base_s": 0.05,
                           "pool": pool,
                           **(link_extra or {})}},
    })
    return FakePoolBackend(cfg)


async def _collect_stream(backend, content, max_tokens=4):
    from symmetry_tpu.provider.backends.base import InferenceRequest

    text = []
    async for chunk in backend.stream(InferenceRequest(
            messages=[{"role": "user", "content": content}],
            max_tokens=max_tokens, temperature=0.0)):
        if chunk.text:
            text.append(chunk.text)
    return "".join(text)


class TestPoolBackendFake:
    def test_2x2_serves_and_spreads_placements(self):
        async def main():
            backend = _fake_pool_backend({"prefill": 2, "decode": 2})
            await backend.start()
            try:
                texts = await asyncio.gather(
                    *[_collect_stream(backend, f"req {i}")
                      for i in range(4)])
                assert all(texts)
                stats = await backend.engine_stats()
                pool = stats["disagg"]["pool"]
                assert pool["healthy"] == {"prefill": 2, "decode": 2}
                assert pool["re_placements"] == 0
                # placements spread: every member served at least once
                # (4 concurrent requests, least-loaded placement)
                per_node = {mid: m["placements"]
                            for mid, m in pool["members"].items()}
                assert all(per_node[f"prefill-{i}"] >= 1
                           for i in range(2)), per_node
                assert all(per_node[f"decode-{i}"] >= 1
                           for i in range(2)), per_node
                # handoff ledger rode the member links
                assert stats["disagg"]["handoff_frames"] == 4
                links = pool["links"]
                assert all(l["connected"] for l in links.values())
                assert sum(l["wire_frames"]
                           for l in links.values()) == 4
            finally:
                await backend.stop()

        run_async(main())

    def test_node_death_re_places_in_flight_on_survivor(self):
        """THE churn contract: killing one prefill member of a 2×1 pool
        mid-traffic completes every in-flight request via re-placement
        — zero failed client outcomes, zero decode-host restarts."""
        async def main():
            backend = _fake_pool_backend({"prefill": 2, "decode": 1})
            await backend.start()
            try:
                tasks = [asyncio.ensure_future(
                    _collect_stream(backend, f"req {i}"))
                    for i in range(4)]
                await asyncio.sleep(0.05)  # inside the prefill window
                await backend._inline_nodes[0].kill()  # crash, no leave
                done = await asyncio.gather(*tasks,
                                            return_exceptions=True)
                errs = [d for d in done if isinstance(d, Exception)]
                assert not errs, f"client-visible failures: {errs}"
                assert all(done)
                stats = await backend.engine_stats()
                pool = stats["disagg"]["pool"]
                states = {mid: m["state"]
                          for mid, m in pool["members"].items()}
                assert states["prefill-0"] == "lost"
                assert states["prefill-1"] == "healthy"
                assert states["decode-0"] == "healthy"
                assert pool["re_placements"] >= 1
                assert stats["supervisor"]["restarts"] == 0
            finally:
                await backend.stop()

        run_async(main())

    def test_link_cut_sheds_then_hot_rejoins(self):
        """A cable pull (link drop, node alive) re-places in-flight
        work; the reconnect loop re-establishes the link and the member
        REJOINS the placement set."""
        async def main():
            backend = _fake_pool_backend({"prefill": 2, "decode": 1})
            await backend.start()
            try:
                t = asyncio.ensure_future(
                    _collect_stream(backend, "req"))
                await asyncio.sleep(0.05)
                # hard-cut the LOADED member's link mid-flight (the
                # node survives — this is a cable pull, not a death)
                held = next(iter(backend._pool._assigned.values()),
                            "prefill-0")
                await backend._plinks[held]._link.drop("test cable pull")
                text = await asyncio.wait_for(t, 30)
                assert text  # completed through re-place or reconnect
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if backend._pool.healthy_count("prefill") == 2:
                        break
                    await asyncio.sleep(0.05)
                stats = await backend.engine_stats()
                pool = stats["disagg"]["pool"]
                assert pool["healthy"]["prefill"] == 2, pool["members"]
                assert pool["rejoins"] >= 1
            finally:
                await backend.stop()

        run_async(main())

    def test_drain_excludes_node_and_finishes_in_flight(self):
        async def main():
            backend = _fake_pool_backend({"prefill": 2, "decode": 1})
            await backend.start()
            try:
                # one request in flight on whichever member won it
                t = asyncio.ensure_future(
                    _collect_stream(backend, "inflight"))
                await asyncio.sleep(0.05)
                held = next(iter(backend._pool._assigned.values()), None)
                idx = 0 if held == "prefill-0" else 1
                await backend._inline_nodes[idx].drain()
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline:
                    m = backend._pool.get(f"prefill-{idx}")
                    if m.state == "draining":
                        break
                    await asyncio.sleep(0.02)
                assert backend._pool.get(
                    f"prefill-{idx}").state == "draining"
                # the in-flight request still completes on the drainer
                assert await asyncio.wait_for(t, 30)
                # every NEW request avoids the draining member
                texts = await asyncio.gather(
                    *[_collect_stream(backend, f"post {i}")
                      for i in range(3)])
                assert all(texts)
                stats = await backend.engine_stats()
                pool = stats["disagg"]["pool"]
                drained = pool["members"][f"prefill-{idx}"]
                other = pool["members"][f"prefill-{1 - idx}"]
                assert drained["state"] == "draining"
                assert other["placements"] >= 3
                assert pool["drains"] == 1
            finally:
                await backend.stop()

        run_async(main())

    def test_pool_of_1x1_serves_and_total_loss_sheds_retryable(self):
        """Degenerate pool: one member per tier serves like the pair;
        losing the ONLY prefill member has no survivor, so the shed is
        the structured retryable — the PR 7/9 link-down behavior."""
        from symmetry_tpu.provider.backends.base import (
            BackendRestartingError)

        async def main():
            backend = _fake_pool_backend({"prefill": 1, "decode": 1})
            await backend.start()
            try:
                assert await _collect_stream(backend, "warm")
                t = asyncio.ensure_future(
                    _collect_stream(backend, "doomed"))
                await asyncio.sleep(0.05)
                await backend._inline_nodes[0].stop()
                with pytest.raises(BackendRestartingError):
                    await asyncio.wait_for(t, 30)
                # new submits shed retryable too (no healthy member)
                with pytest.raises(BackendRestartingError):
                    await _collect_stream(backend, "after")
                stats = await backend.engine_stats()
                pool = stats["disagg"]["pool"]
                assert pool["members"]["prefill-0"]["state"] == "lost"
                assert pool["healthy"]["prefill"] == 0
            finally:
                await backend.stop()

        run_async(main())

    def test_decode_member_death_sheds_only_its_streams(self):
        """Per-member supervision: a decode member's death fails only
        the streams adopted THERE (retryable), and the member respawns
        alone — its sibling keeps serving throughout."""
        async def main():
            backend = _fake_pool_backend({"prefill": 1, "decode": 2},
                                         token_delay_s=0.3)
            await backend.start()
            try:
                tasks = [asyncio.ensure_future(
                    _collect_stream(backend, f"req {i}", max_tokens=8))
                    for i in range(2)]
                # wait until both are adopted (one per decode member)
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if len(backend._pool._adopted) == 2:
                        break
                    await asyncio.sleep(0.02)
                adopted = dict(backend._pool._adopted)
                assert set(adopted.values()) == {"decode-0", "decode-1"}
                victim = backend._decode_members["decode-0"]
                victim.proc.kill()
                done = await asyncio.gather(*tasks,
                                            return_exceptions=True)
                from symmetry_tpu.provider.backends.base import (
                    BackendRestartingError)

                sheds = [d for d in done
                         if isinstance(d, BackendRestartingError)]
                texts = [d for d in done if isinstance(d, str)]
                assert len(sheds) == 1, done  # only the victim's stream
                assert len(texts) == 1 and texts[0]
                # the victim respawns alone
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if victim.alive and victim.restarts >= 1:
                        break
                    await asyncio.sleep(0.05)
                assert victim.restarts == 1
                sibling = backend._decode_members["decode-1"]
                assert sibling.restarts == 0 and sibling.alive
                assert await _collect_stream(backend, "after")
            finally:
                await backend.stop()

        run_async(main())


class TestCreditGate:
    def test_acquire_blocks_until_grant(self):
        async def main():
            gate = CreditGate(10)
            await gate.acquire(10)  # window exhausted
            acquired = asyncio.Event()

            async def taker():
                await gate.acquire(5)
                acquired.set()

            t = asyncio.ensure_future(taker())
            await asyncio.sleep(0.02)
            assert not acquired.is_set()
            gate.grant(3)  # not enough yet
            await asyncio.sleep(0.02)
            assert not acquired.is_set()
            gate.grant(3)
            await asyncio.wait_for(acquired.wait(), 2)
            assert gate.stats["credit_stalls"] == 1
            assert gate.available == 1
            t.cancel()

        run_async(main())


class TestAdoptLeakRegression:
    """Regression for the real L402 symlint's lifecycle checker found
    in adopt_prefix: the row assembly between plan_insert and the
    scatter ran OUTSIDE the abort guard, so a failure there (no bucket
    fits, a malformed frame, a device transfer error) leaked the
    plan's pinned prefix and allocated blocks forever."""

    def test_scatter_failure_aborts_plan_and_state_survives(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, role="decode")
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16,
            gqa_arrays(L=cfg.num_layers, K=cfg.num_kv_heads,
                       D=cfg.dim_per_head, p=16)))
        real = engine._write_blocks

        def boom(*a, **kw):
            raise RuntimeError("scatter failed")

        engine._write_blocks = boom
        with pytest.raises(RuntimeError, match="scatter failed"):
            engine.adopt_prefix(h)
        pool = engine.prefix_index.pool
        # plan aborted: nothing pinned, every allocated block returned
        assert pool.pinned == 0 and pool.in_use == 0
        # the store is uncorrupted — the same frame adopts cleanly once
        # the device cooperates again
        engine._write_blocks = real
        assert engine.adopt_prefix(h) is True
        assert engine.prefix_index.match_len(list(range(20))) == 16
