"""Disaggregated prefill/decode: KV handoff frames, roles, and identity.

Covers the acceptance surface of the disagg PR:

  - frame codec: round-trip across GQA kv_dim shapes, int8-quantized
    caches (scale planes), bf16 payloads, routing-only (p == 0) frames;
    truncated/corrupt/wrong-version/wrong-shape frames are REJECTED
    (versioned header + crc — bad frames must never adopt as KV)
  - broker: per-role config derivation (role pinned, decode tier's
    prefix cache defaulted, per-tier faults), request-state migration
    (adopt op carries sampling/max_new, deadline rebased by prefill-tier
    time), unknown/cancelled ids dropped
  - engine roles: construction contracts (decode needs the prefix
    store, prefill needs a chunk size, mesh refused), adoption rejects
    geometry/dtype/alignment mismatches, budget rejection degrades to
    full prefill
  - THE contract: greedy decode is token-identical between a unified
    engine and an in-process prefill-role → frames → decode-role pair,
    across short (routing-only), single-dispatch, and multi-chunk
    prompts — with per-role scheduler accounting (a decode host books
    adoption, not admission prefill; a prefill host books handoffs)
  - host wire ops: the prefill host's handoff frame emit (counters,
    short-prompt fast path) and the decode host's adopt op (corrupt
    frame → error event, never a submit)
  - the CROSS-MACHINE handoff link (engine/disagg/net.py): envelope
    reassembly over a transport that fragments and coalesces
    arbitrarily, corrupt-transfer nak → retransmit, mid-stream
    disconnect → zero partial adoptions, credit-window backpressure,
    ack-timeout retry exhaustion → fail, link clock reconciliation
    under deliberate skew, and the disagg.net.* fault seams
"""

import asyncio
import json
import random
import struct
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.disagg import (
    DEFAULT_DECODE_PREFIX_MB,
    FrameError,
    HandoffBroker,
    decode_kv_handoff,
    derive_role_config,
    encode_kv_handoff,
)
from symmetry_tpu.engine.engine import EngineError, InferenceEngine, SamplingParams
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import init_params, preset


# ---------------------------------------------------------------------
# Frame codec


def gqa_arrays(L=3, K=2, D=8, p=16, dtype=np.float32):
    """kv_heads != heads — the GQA shape the frames must round-trip."""
    rng = np.random.default_rng(0)
    return {
        "k": rng.standard_normal((L, 1, p, K, D)).astype(dtype),
        "v": rng.standard_normal((L, 1, p, K, D)).astype(dtype),
    }


class TestFrames:
    def test_roundtrip_gqa_f32(self):
        arrays = gqa_arrays()
        tokens = list(range(20))
        buf = encode_kv_handoff("req-1", tokens, 16, arrays)
        h = decode_kv_handoff(buf)
        assert h.request_id == "req-1"
        assert h.tokens == tuple(tokens)
        assert h.p == 16 and not h.kv_quant
        np.testing.assert_array_equal(h.arrays["k"], arrays["k"])
        np.testing.assert_array_equal(h.arrays["v"], arrays["v"])

    def test_roundtrip_int8_quantized(self):
        L, K, p = 2, 4, 8
        arrays = {
            "k": np.arange(L * p * K * 4, dtype=np.int8).reshape(
                L, 1, p, K, 4),
            "v": np.ones((L, 1, p, K, 4), np.int8),
            "k_scale": np.full((L, 1, K, p), 0.5, np.float32),
            "v_scale": np.full((L, 1, K, p), 0.25, np.float32),
        }
        buf = encode_kv_handoff("q", list(range(10)), p, arrays,
                                kv_quant=True)
        h = decode_kv_handoff(buf)
        assert h.kv_quant
        np.testing.assert_array_equal(h.arrays["k_scale"],
                                      arrays["k_scale"])
        assert h.arrays["k"].dtype == np.int8

    def test_roundtrip_bf16(self):
        import ml_dtypes

        arrays = {k: v.astype(ml_dtypes.bfloat16)
                  for k, v in gqa_arrays(p=8).items()}
        h = decode_kv_handoff(encode_kv_handoff("b", list(range(9)), 8,
                                                arrays))
        assert h.arrays["k"].dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(h.arrays["k"], arrays["k"])

    def test_routing_only_frame(self):
        h = decode_kv_handoff(encode_kv_handoff("r0", [1, 2, 3], 0, None))
        assert h.p == 0 and h.arrays == {} and h.tokens == (1, 2, 3)

    def test_multi_chunk_prefix_roundtrip(self):
        """A prefix spanning several prefill chunks is still ONE frame —
        the codec carries whatever p the prefill tier built."""
        arrays = gqa_arrays(p=48)  # 6 chunks at chunk=8
        h = decode_kv_handoff(encode_kv_handoff("m", list(range(50)), 48,
                                                arrays))
        assert h.p == 48 and h.arrays["k"].shape[2] == 48

    def test_truncated_rejected(self):
        buf = encode_kv_handoff("t", list(range(20)), 16, gqa_arrays())
        for cut in (0, 4, 10, len(buf) // 2, len(buf) - 1):
            with pytest.raises(FrameError):
                decode_kv_handoff(buf[:cut])

    def test_corrupt_payload_rejected(self):
        buf = bytearray(encode_kv_handoff("c", list(range(20)), 16,
                                          gqa_arrays()))
        buf[len(buf) // 2] ^= 0xFF
        with pytest.raises(FrameError, match="checksum"):
            decode_kv_handoff(bytes(buf))

    def test_wrong_version_rejected(self):
        buf = bytearray(encode_kv_handoff("v", list(range(20)), 16,
                                          gqa_arrays()))
        buf[4:6] = struct.pack("<H", 99)
        with pytest.raises(FrameError, match="version"):
            decode_kv_handoff(bytes(buf))

    def test_bad_magic_rejected(self):
        buf = encode_kv_handoff("m", list(range(20)), 16, gqa_arrays())
        with pytest.raises(FrameError, match="magic"):
            decode_kv_handoff(b"NOPE" + buf[4:])

    def test_shape_and_plane_validation(self):
        arrays = gqa_arrays(p=16)
        # p axis disagreeing with meta is caught at decode
        bad = dict(arrays)
        bad["k"] = arrays["k"][:, :, :8]
        with pytest.raises(FrameError):
            decode_kv_handoff(encode_kv_handoff("s", list(range(20)), 16,
                                                bad))
        # encoder itself enforces plane presence
        with pytest.raises(ValueError, match="missing KV planes"):
            encode_kv_handoff("s", list(range(20)), 16, {"k": arrays["k"]})
        # quantized frame without scale planes
        with pytest.raises(ValueError, match="missing KV planes"):
            encode_kv_handoff("s", list(range(20)), 16, arrays,
                              kv_quant=True)
        # p beyond the prompt
        with pytest.raises(ValueError):
            encode_kv_handoff("s", [1, 2], 16, arrays)

    def test_decoder_shape_validation(self):
        """A structurally-valid frame whose meta lies about shapes is
        still rejected (defense against a buggy/mismatched peer)."""
        arrays = gqa_arrays(p=16)
        buf = encode_kv_handoff("d", list(range(20)), 16, arrays)
        # splice the meta: claim p=8 while arrays carry 16
        from symmetry_tpu.engine.disagg import encode_frame

        meta = {"id": "d", "tokens": list(range(20)), "p": 8,
                "kv_quant": False}
        forged = encode_frame(meta, arrays)
        with pytest.raises(FrameError):
            decode_kv_handoff(forged)
        assert decode_kv_handoff(buf).p == 16  # control


# ---------------------------------------------------------------------
# Broker


BASE_CFG = {
    "name": "p", "public": True, "serverKey": "00" * 32,
    "modelName": "tiny:test", "apiProvider": "tpu_native",
    "tpu": {"role": "disagg", "model_preset": "tiny",
            "max_batch_size": 4,
            "disagg": {"prefill": {"faults": {"disagg.handoff": "crash"}},
                       "decode": {"max_batch_size": 8}}},
}


class TestBroker:
    def test_derive_role_configs(self):
        pre = derive_role_config(BASE_CFG, "prefill")
        dec = derive_role_config(BASE_CFG, "decode")
        assert pre["tpu"]["role"] == "prefill"
        assert dec["tpu"]["role"] == "decode"
        # per-tier overrides land in the tier's tpu section only
        assert pre["tpu"]["max_batch_size"] == 4
        assert dec["tpu"]["max_batch_size"] == 8
        # tier faults land TOP-LEVEL on that host only
        assert pre["faults"] == {"disagg.handoff": "crash"}
        assert "faults" not in dec
        # decode tier gets a prefix-cache budget by default
        assert dec["tpu"]["prefix_cache_mb"] == DEFAULT_DECODE_PREFIX_MB
        assert "prefix_cache_mb" not in pre["tpu"]
        # neither derived config keeps the disagg mapping (a tier host
        # must not recurse)
        assert "disagg" not in pre["tpu"] and "disagg" not in dec["tpu"]
        # the source mapping is untouched
        assert BASE_CFG["tpu"]["role"] == "disagg"

    def test_adopt_op_migrates_state_and_rebases_deadline(self):
        broker = HandoffBroker()
        broker.note_submit("r1", {
            "op": "submit", "id": "r1", "messages": [{"role": "user"}],
            "max_new": 32, "sampling": {"temperature": 0.5, "seed": 7},
            "trace": "t-1", "deadline_s": 10.0})
        time.sleep(0.05)
        op = broker.adopt_op({"id": "r1", "p": 16, "nbytes": 1234,
                              "frame": "QUJD"})
        assert op["op"] == "adopt" and op["id"] == "r1"
        assert op["frame"] == "QUJD"
        assert op["max_new"] == 32
        assert op["sampling"] == {"temperature": 0.5, "seed": 7}
        assert op["trace"] == "t-1"
        assert "messages" not in op  # tokens ride the frame
        assert 9.0 < op["deadline_s"] < 10.0  # rebased, not reset
        assert broker.counters["handoff_frames"] == 1
        assert broker.counters["handoff_bytes"] == 1234
        assert broker.counters["prefix_tokens"] == 16
        assert broker.pending == 0
        assert broker.prefill_tier_hist.count == 1

    def test_unknown_or_forgotten_id_drops_frame(self):
        broker = HandoffBroker()
        assert broker.adopt_op({"id": "ghost", "p": 0}) is None
        broker.note_submit("r2", {"max_new": 8})
        broker.forget("r2")  # cancelled before the handoff came back
        assert broker.adopt_op({"id": "r2", "p": 0}) is None
        assert broker.counters["dropped"] == 1
        stats = broker.stats()
        assert stats["submitted"] == 1 and stats["pending"] == 0

    def test_fail_all_clears_pending(self):
        broker = HandoffBroker()
        broker.note_submit("a", {})
        broker.note_submit("b", {})
        broker.fail_all()
        assert broker.pending == 0
        assert broker.counters["dropped"] == 2


# ---------------------------------------------------------------------
# Engine roles + the token-identity contract


@pytest.fixture(scope="module")
def setup():
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    return cfg, params


def make_engine(cfg, params, role="unified", cache_mb=16, chunk=8,
                slots=4, **kw):
    return InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=slots, max_seq_len=64,
        prefill_buckets=(16, 32), cache_dtype=jnp.float32,
        prefill_chunk=chunk, prefix_cache_bytes=int(cache_mb * 2**20),
        role=role, **kw)


def drive(sched, prompts, max_new=6, timeout=120):
    """Submit greedy requests; returns [(text, finish_reason, error)]."""
    done = threading.Event()
    out = [None] * len(prompts)
    texts = [[] for _ in prompts]
    remaining = [len(prompts)]

    def mk(i):
        def emit(ev):
            texts[i].append(ev.text)
            if ev.done:
                out[i] = ("".join(texts[i]), ev.finish_reason, ev.error)
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.set()
        return emit

    for i, ids in enumerate(prompts):
        sched.submit(GenRequest(prompt_ids=list(ids),
                                sampling=SamplingParams(),
                                max_new_tokens=max_new, emit=mk(i),
                                id=f"r{i}"))
    assert done.wait(timeout), f"streams incomplete: {out}"
    return out


def host_style_handoff(engine, slot, req):
    """What the prefill host's sink does: extract the aligned slot-lane
    KV and serialize it (the real sink lives in engine/host.py; this
    mirrors it so the identity test exercises the same frame path)."""
    n = len(req.prompt_ids)
    A = engine.prefix_align
    p = A * ((n - 1) // A)
    arrays = None
    if p > 0:
        cache = engine.extract_slot_kv(slot, p)
        arrays = {"k": np.asarray(cache.k)[:, :, :p],
                  "v": np.asarray(cache.v)[:, :, :p]}
        if engine.kv_quant:
            arrays["k_scale"] = np.asarray(cache.k_scale)[:, :, :, :p]
            arrays["v_scale"] = np.asarray(cache.v_scale)[:, :, :, :p]
    return encode_kv_handoff(req.id, req.prompt_ids, p, arrays,
                             kv_quant=engine.kv_quant)


PROMPTS = [
    list(b"hello world prefix!"),            # 19 toks → p=16, 1 dispatch
    list(b"hi"),                             # 2 toks → p=0 routing-only
    list(b"a longer prompt that needs chunked prefill")[:30],  # p=24,
                                             # multi-chunk at chunk=8
    list(b"hello world prefill"),            # shares aligned prefix w/ #0
]


class TestRoleContracts:
    def test_bad_role_rejected(self, setup):
        cfg, params = setup
        with pytest.raises(EngineError, match="unknown engine role"):
            make_engine(cfg, params, role="disagg")

    def test_decode_role_requires_prefix_store(self, setup):
        cfg, params = setup
        with pytest.raises(EngineError, match="prefix cache"):
            make_engine(cfg, params, role="decode", cache_mb=0)

    def test_prefill_role_requires_chunk(self, setup):
        cfg, params = setup
        with pytest.raises(EngineError, match="prefill_chunk"):
            make_engine(cfg, params, role="prefill", chunk=None)

    def test_prefill_scheduler_requires_sink(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, role="prefill")
        with pytest.raises(ValueError, match="handoff sink"):
            Scheduler(engine)

    def test_adoption_rejects_mismatches(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, role="decode")
        good = gqa_arrays(L=cfg.num_layers, K=cfg.num_kv_heads,
                          D=cfg.dim_per_head, p=16)
        # wrong layer count
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16,
            gqa_arrays(L=cfg.num_layers + 1, K=cfg.num_kv_heads,
                       D=cfg.dim_per_head, p=16)))
        with pytest.raises(EngineError, match="shape"):
            engine.adopt_prefix(h)
        # wrong dtype (engine cache is f32 here)
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16,
            {k: v.astype(np.float16) for k, v in good.items()}))
        with pytest.raises(EngineError, match="dtype"):
            engine.adopt_prefix(h)
        # quantization mismatch
        qarr = {"k": np.zeros((cfg.num_layers, 1, 16, cfg.num_kv_heads,
                               cfg.dim_per_head), np.int8),
                "v": np.zeros((cfg.num_layers, 1, 16, cfg.num_kv_heads,
                               cfg.dim_per_head), np.int8),
                "k_scale": np.zeros((cfg.num_layers, 1, cfg.num_kv_heads,
                                     16), np.float32),
                "v_scale": np.zeros((cfg.num_layers, 1, cfg.num_kv_heads,
                                     16), np.float32)}
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16, qarr, kv_quant=True))
        with pytest.raises(EngineError, match="quantization"):
            engine.adopt_prefix(h)
        # misaligned prefix length (align is 8 here)
        mis = gqa_arrays(L=cfg.num_layers, K=cfg.num_kv_heads,
                         D=cfg.dim_per_head, p=12)
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 12, mis))
        with pytest.raises(EngineError, match="aligned"):
            engine.adopt_prefix(h)
        # control: a well-formed frame adopts
        h = decode_kv_handoff(encode_kv_handoff(
            "x", list(range(20)), 16, good))
        assert engine.adopt_prefix(h) is True
        assert engine.adopt_prefix(h) is True  # idempotent (has())


class TestDisaggIdentity:
    """THE acceptance contract: greedy disagg == greedy unified."""

    @pytest.fixture(scope="class")
    def reference(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, role="unified", cache_mb=0)
        engine.warmup()
        sched = Scheduler(engine)
        sched.start()
        try:
            return drive(sched, PROMPTS)
        finally:
            sched.stop()

    def test_greedy_token_identical_and_per_role_stats(self, setup,
                                                       reference):
        cfg, params = setup
        eng_p = make_engine(cfg, params, role="prefill")
        eng_p.warmup()
        eng_d = make_engine(cfg, params, role="decode")
        eng_d.warmup()

        frames: dict[str, bytes] = {}
        fallback_events = []

        def handoff(slot, req, first):
            frames[req.id] = host_style_handoff(eng_p, slot, req)

        sched_p = Scheduler(eng_p, handoff=handoff)
        sched_p.start()
        sched_d = Scheduler(eng_d)
        sched_d.start()
        try:
            # Tier 1: prefill-role admission builds KV and hands off.
            for i, ids in enumerate(PROMPTS):
                sched_p.submit(GenRequest(
                    prompt_ids=list(ids), sampling=SamplingParams(),
                    max_new_tokens=6,
                    emit=lambda ev: fallback_events.append(ev),
                    id=f"r{i}"))
            deadline = time.monotonic() + 120
            while len(frames) < len(PROMPTS):
                assert time.monotonic() < deadline, \
                    f"handoffs incomplete: {sorted(frames)}; " \
                    f"events={fallback_events}"
                time.sleep(0.02)
            ps = sched_p.stats()
            assert ps["role"] == "prefill"
            assert ps["handoffs"] == len(PROMPTS)
            assert ps["handoff_s"] > 0
            # prefill tier never decodes: zero blocks, zero tokens
            assert ps["block_syncs"] == 0 and ps["tokens"] == 0
            # no token events ever left the prefill tier
            assert not fallback_events

            # Tier 2: adopt every frame, then run the SAME prompts.
            for i in range(len(PROMPTS)):
                h = decode_kv_handoff(frames[f"r{i}"])
                if h.p:
                    assert eng_d.adopt_prefix(h)
            got = drive(sched_d, PROMPTS)
            assert [g[0] for g in got] == [r[0] for r in reference], \
                "greedy disagg text diverged from unified"
            assert [g[1] for g in got] == [r[1] for r in reference]

            ds = sched_d.stats()
            assert ds["role"] == "decode"
            # Satellite contract: a decode-role host books adoption
            # dispatches, NOT unified-mode admission prefill — the only
            # admit dispatch allowed is the p=0 routing-only prompt's
            # full prefill (which IS admission work, on any tier).
            assert ds["adopt_dispatches"] >= 2  # p=16 unit + p=24 seed
            assert ds["admit_dispatches"] == 1  # the routing-only prompt
            assert ds["adopt_s"] > 0
            assert "adopt_dispatch_s" in ds
        finally:
            sched_p.stop()
            sched_d.stop()

    def test_budget_rejected_adoption_still_token_identical(self, setup,
                                                            reference):
        """A decode tier whose store cannot hold the entry falls back to
        a full prefill — slower, but the stream must be byte-identical."""
        cfg, params = setup
        eng_d = make_engine(cfg, params, role="decode", cache_mb=1e-4)
        # Decode-role construction raises an undersized budget to the
        # geometry floor (2 × largest-bucket entry bytes) — a default
        # too small for the model must never silently reject EVERY
        # adoption.
        assert eng_d.prefix_store.budget_bytes >= \
            2 * 32 * eng_d.kv_bytes_per_token()
        # Simulate a store with no headroom (everything pinned/full):
        # insert() rejects, lookup misses, admission runs the ordinary
        # full-prefill path.
        eng_d.prefix_store.budget_bytes = 64
        eng_d.warmup()
        h = decode_kv_handoff(encode_kv_handoff(
            "r0", PROMPTS[0], 16,
            gqa_arrays(L=cfg.num_layers, K=cfg.num_kv_heads,
                       D=cfg.dim_per_head, p=16)))
        # NOTE: arrays here are random, NOT the true prefix KV — the
        # rejection path must not adopt them, which the identity check
        # below proves (adopted garbage would change the text).
        assert eng_d.adopt_prefix(h) is False
        sched = Scheduler(eng_d)
        sched.start()
        try:
            got = drive(sched, [PROMPTS[0]])
            assert got[0][0] == reference[0][0]
        finally:
            sched.stop()


# ---------------------------------------------------------------------
# Process-level identity: the same contract through REAL engine hosts
# (unified single host vs disagg pair), greedy, over the host pipes.


@pytest.mark.slow
class TestBackendDisaggIdentity:
    @staticmethod
    def _cfg(role, disagg_net=None):
        from symmetry_tpu.provider.config import ConfigManager

        return ConfigManager(config={
            "name": "disagg-id", "public": False, "serverKey": "00" * 32,
            "modelName": "tiny:test", "apiProvider": "tpu_native",
            "dataCollectionEnabled": False,
            "tpu": {"model_preset": "tiny", "dtype": "float32",
                    "max_batch_size": 4, "max_seq_len": 128,
                    "prefill_buckets": [32, 64], "prefill_chunk": 16,
                    "engine_isolation": "process", "role": role,
                    **({"disagg": disagg_net} if disagg_net else {})},
        })

    CONTENTS = ["tell me about disagg serving",  # multi-chunk prefix
                "hi"]  # minimal prompt (template still spans align)

    @classmethod
    def _collect_all(cls, role, disagg_net=None):
        import asyncio

        from symmetry_tpu.provider.backends.base import InferenceRequest
        from symmetry_tpu.provider.backends.tpu_native import (
            TpuNativeBackend)

        async def go():
            backend = TpuNativeBackend(cls._cfg(role, disagg_net))
            await backend.start()
            try:
                out = []
                for content in cls.CONTENTS:
                    text = []
                    async for chunk in backend.stream(InferenceRequest(
                            messages=[{"role": "user",
                                       "content": content}],
                            max_tokens=8, temperature=0.0)):
                        if chunk.text:
                            text.append(chunk.text)
                    out.append("".join(text))
                stats = await backend.engine_stats()
                return out, stats
            finally:
                await backend.stop()

        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(go(), 600))

    def test_process_mode_greedy_identity(self):
        unified, _ = self._collect_all("unified")
        disagg, stats = self._collect_all("disagg")
        assert disagg == unified, \
            "greedy disagg diverged from unified through real host pipes"
        dg = stats.get("disagg") or {}
        assert dg.get("handoff_frames") == 2
        # The chat template alone spans the 16-token alignment, so even
        # "hi" ships real KV (routing-only is covered at the host layer
        # in TestHostWireOps).
        assert dg.get("routing_only") == 0
        assert dg.get("handoff_bytes", 0) > 0
        assert (dg.get("prefill_host") or {}).get("role") == "prefill"

    def test_network_mode_tcp_greedy_identity(self):
        """THE cross-machine acceptance contract: both tiers as real
        engine hosts connected ONLY through the TCP handoff link
        (chunked, credit-gated, acked) — greedy output must be
        token-identical to unified, and the wire-split stats must be
        populated."""
        unified, _ = self._collect_all("unified")
        disagg, stats = self._collect_all(
            "disagg", disagg_net={"peer": "tcp://127.0.0.1:0",
                                  "inline": True, "chunk_kb": 4})
        assert disagg == unified, \
            "greedy disagg-over-TCP diverged from unified"
        dg = stats.get("disagg") or {}
        assert dg.get("handoff_frames") == 2
        assert dg.get("wire_frames") == 2
        assert (dg.get("wire_s") or {}).get("count") == 2
        assert dg.get("handoff_bytes", 0) > 0
        assert (dg.get("prefill_host") or {}).get("role") == "prefill"
        link = dg.get("link") or {}
        assert link.get("connected") is True
        assert link.get("partial_discards") == 0
        node = dg.get("node") or {}
        assert node.get("handoffs_sent") == 2
        assert node.get("retries") == 0


# ---------------------------------------------------------------------
# Host wire ops (no subprocess: EngineHost methods against stub engines)


class _StubPrefillEngine:
    prefix_align = 8
    kv_quant = False

    def __init__(self, cfg, params):
        self._real = None  # unused; extract served from canned arrays
        self.calls = []

    def kv_bytes_per_token(self):
        return 2 * 2 * 2 * 4 * 4  # 2 planes × L2 × K2 × D4 × f32

    def extract_slot_kv(self, slot, p):
        import jax.numpy as jnp

        from symmetry_tpu.models.llama import KVCache

        self.calls.append((slot, p))
        return KVCache(k=jnp.zeros((2, 1, 32, 2, 4), jnp.float32),
                       v=jnp.zeros((2, 1, 32, 2, 4), jnp.float32),
                       lengths=jnp.full((1,), p, jnp.int32))


class TestHostWireOps:
    def _host(self, role):
        from symmetry_tpu.engine.host import EngineHost

        host = EngineHost(config=None)
        host._role = role
        return host

    def test_handoff_sink_emits_frame(self, setup, capsys):
        host = self._host("prefill")
        host._engine = _StubPrefillEngine(*setup)
        req = GenRequest(prompt_ids=list(range(20)),
                         sampling=SamplingParams(), max_new_tokens=4,
                         emit=lambda ev: None, id="h1")
        host._reported["h1"] = 0
        host._handoff_sink(2, req, 99)
        line = json.loads(capsys.readouterr().out.strip())
        assert line["op"] == "handoff" and line["id"] == "h1"
        assert line["p"] == 16 and line["prompt_len"] == 20
        import base64

        h = decode_kv_handoff(base64.b64decode(line["frame"]))
        assert h.p == 16 and h.arrays["k"].shape == (2, 1, 16, 2, 4)
        assert line["nbytes"] == len(base64.b64decode(line["frame"]))
        assert host.handoff_stats["frames"] == 1
        assert host.handoff_stats["prefix_tokens"] == 16
        assert "h1" not in host._reported  # ownership moved tiers
        assert host._engine.calls == [(2, 16)]

    def test_routing_only_fast_path_no_extract(self, setup, capsys):
        host = self._host("prefill")
        host._engine = _StubPrefillEngine(*setup)
        host._emit_handoff("h2", [1, 2, 3], 0, None)
        line = json.loads(capsys.readouterr().out.strip())
        assert line["p"] == 0
        assert host._engine.calls == []  # no device work for p=0
        assert host.handoff_stats["routing_only"] == 1

    def _submitting_host(self):
        host = self._host("decode")
        submits = []
        host._scheduler = type("S", (), {
            "submit": lambda self, req: submits.append(req)})()
        return host, submits

    def test_adopt_defers_frame_work_to_engine_thread_thunk(self, capsys):
        """The adopt op submits WITHOUT parsing the frame (the serial
        command loop must never pay for a multi-hundred-MB decode); the
        thunk — run by the scheduler on the engine thread — parses,
        fills prompt_ids, and adopts."""
        import base64

        host, submits = self._submitting_host()
        adopted = []
        host._engine = type("E", (), {
            "adopt_prefix": lambda self, h: adopted.append(h.p) or True})()
        tokens = list(range(20))
        frame = encode_kv_handoff("r8", tokens, 16, gqa_arrays())
        host._handle_adopt({"op": "adopt", "id": "r8",
                            "frame": base64.b64encode(frame).decode(),
                            "max_new": 4})
        assert len(submits) == 1
        req = submits[0]
        assert req.prompt_ids == []  # frame not parsed yet
        assert host.adopt_stats["frames"] == 0
        req.adopt(req)
        assert req.prompt_ids == tokens  # thunk filled it
        assert adopted == [16]
        assert host.adopt_stats["frames"] == 1
        assert host.adopt_stats["adopted"] == 1
        assert host.adopt_stats["bytes"] == len(frame)

    def test_adopt_corrupt_frame_fails_in_thunk(self, capsys):
        import base64

        host, submits = self._submitting_host()
        bad = bytearray(encode_kv_handoff("r9", list(range(20)), 16,
                                          gqa_arrays()))
        bad[60] ^= 0xFF
        host._handle_adopt({"op": "adopt", "id": "r9",
                            "frame": base64.b64encode(bytes(bad)).decode(),
                            "max_new": 4})
        assert len(submits) == 1
        with pytest.raises(RuntimeError, match="adoption failed"):
            submits[0].adopt(submits[0])
        assert host.adopt_stats["errors"] == 1
        assert host.adopt_stats["frames"] == 0  # nothing adopted

    def test_adopt_id_mismatch_fails_in_thunk(self):
        import base64

        host, submits = self._submitting_host()
        frame = encode_kv_handoff("other", [1, 2, 3], 0, None)
        host._handle_adopt({"op": "adopt", "id": "mine",
                            "frame": base64.b64encode(frame).decode()})
        with pytest.raises(RuntimeError, match="adoption failed"):
            submits[0].adopt(submits[0])
        assert host.adopt_stats["errors"] == 1

    def test_adopt_missing_frame_is_immediate_error_event(self, capsys):
        host, submits = self._submitting_host()
        host._handle_adopt({"op": "adopt", "id": "r10", "max_new": 4})
        line = json.loads(capsys.readouterr().out.strip())
        assert line["finish_reason"] == "error"
        assert "no frame" in line["error"]
        assert submits == []
        assert host.adopt_stats["errors"] == 1


# ---------------------------------------------------------------------
# Cross-machine handoff link (engine/disagg/net.py)


from symmetry_tpu.engine.disagg.net import (  # noqa: E402
    CreditGate,
    HandoffLink,
    LinkConfig,
    LinkDecoder,
    LinkError,
    PrefillLink,
    Reassembler,
    encode_link_msg,
    link_clock_handshake,
)
from symmetry_tpu.protocol.keys import HOST_OPS, LINK_OPS, LinkOp  # noqa: E402
from symmetry_tpu.transport.base import Connection  # noqa: E402
from symmetry_tpu.transport.memory import memory_pair  # noqa: E402
from symmetry_tpu.utils.faults import FAULTS  # noqa: E402


def run_async(coro, timeout=60):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


class _RechunkConnection(Connection):
    """Proxy that deliberately violates every frame boundary: inbound
    bytes are re-sliced at seeded-random offsets (fragmenting AND
    coalescing), which is exactly what the link's streaming envelope
    decoder must survive."""

    def __init__(self, inner, seed=0):
        self._inner = inner
        self._rng = random.Random(seed)
        self._buf = bytearray()
        self._eof = False

    async def send(self, frame):
        await self._inner.send(frame)

    async def recv(self):
        while not self._buf:
            if self._eof:
                return None
            f = await self._inner.recv()
            if f is None:
                self._eof = True
                break
            self._buf += f
        if not self._buf:
            return None
        n = self._rng.randint(1, min(len(self._buf), 97))
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    async def close(self):
        await self._inner.close()

    @property
    def closed(self):
        return self._inner.closed


class _ManglingConnection(Connection):
    """Proxy that flips the LAST byte of the Nth outbound frame — for a
    link `chunk` message that byte is frame payload, so the transfer's
    CRC check must catch it and nak."""

    def __init__(self, inner, mangle_frame):
        self._inner = inner
        self._mangle_frame = mangle_frame
        self._n = 0

    async def send(self, frame):
        self._n += 1
        if self._n == self._mangle_frame:
            frame = frame[:-1] + bytes([frame[-1] ^ 0xFF])
        await self._inner.send(frame)

    async def recv(self):
        return await self._inner.recv()

    async def close(self):
        await self._inner.close()

    @property
    def closed(self):
        return self._inner.closed


class TestLinkEnvelope:
    def test_roundtrip_under_arbitrary_fragmentation(self):
        rng = random.Random(11)
        msgs = [({"op": "chunk", "seq": i},
                 rng.randbytes(rng.randint(0, 4096)))
                for i in range(32)]
        blob = b"".join(encode_link_msg(h, p) for h, p in msgs)
        for seed in range(3):
            r = random.Random(seed)
            dec = LinkDecoder()
            out = []
            i = 0
            while i < len(blob):
                n = r.randint(1, 513)
                out.extend(dec.feed(blob[i:i + n]))
                i += n
            assert [(h["seq"], p) for h, p in out] \
                == [(h["seq"], p) for h, p in msgs]

    def test_bad_magic_rejected(self):
        dec = LinkDecoder()
        with pytest.raises(LinkError, match="magic"):
            list(dec.feed(b"XXXX" + b"\x00" * 12))

    def test_oversized_header_rejected(self):
        bad = struct.pack("<4sII", b"SYLK", 1 << 24, 0)
        with pytest.raises(LinkError, match="too large"):
            list(LinkDecoder().feed(bad))

    def test_registry_pins_link_ops(self):
        # Every wire op the link protocol speaks is registered — the
        # wire-contract checker pivots on this set (no raw literals
        # outside tests), and the deliberate HostOp value reuse (a link
        # `submit` forwards a host `submit`) is pinned as intentional.
        assert LINK_OPS == {"hello", "clock", "submit", "cancel",
                            "stats", "trace", "credit", "ack", "nak",
                            "begin", "chunk", "end", "fail", "event"}
        assert LINK_OPS & HOST_OPS == {"clock", "submit", "cancel",
                                       "stats", "trace", "event"}


class _MiniDecodePump:
    """The decode side of the bulk path, driven manually: the REAL
    DecodeLink pump internals (Reassembler + credit grants + ack/nak)
    without the dial loop, so each test controls the link lifetime."""

    def __init__(self, conn, *, ack=True):
        self.link = HandoffLink(conn)
        self.reasm = Reassembler()
        self.got = []
        self.fails = []
        self.ack = ack

    async def run(self):
        while True:
            msg = await self.link.recv()
            if msg is None:
                return
            header, payload = msg
            op = header.get("op")
            try:
                if op == LinkOp.CHUNK:
                    await self.link.send({"op": LinkOp.CREDIT,
                                          "n": len(payload)})
                    self.reasm.chunk(header, payload)
                elif op == LinkOp.BEGIN:
                    self.reasm.begin(header)
                elif op == LinkOp.END:
                    meta, frame = self.reasm.end(header)
                    if self.ack:
                        self.got.append((meta, frame))
                        await self.link.send(
                            {"op": LinkOp.ACK,
                             "xfer": header.get("xfer")})
                elif op == LinkOp.FAIL:
                    self.fails.append(header)
            except LinkError as exc:
                if self.link.closed or "send failed" in str(exc):
                    return  # peer reset the link mid-message
                await self.link.send({"op": LinkOp.NAK,
                                      "xfer": header.get("xfer")})


def _plink(conn, **cfg_overrides):
    cfg = LinkConfig({"chunk_kb": 1, **cfg_overrides})
    return PrefillLink(HandoffLink(conn), cfg,
                       on_command=lambda line: None,
                       on_probe=lambda op: None)


class TestLinkTransfer:
    FRAME = encode_kv_handoff("w1", list(range(40)), 32,
                              gqa_arrays(p=32))

    def test_multi_chunk_reassembly_over_fragmenting_transport(self):
        async def main():
            a, b = memory_pair()
            pump = _MiniDecodePump(_RechunkConnection(a, seed=3))
            plink = _plink(b)
            t1 = asyncio.ensure_future(pump.run())
            t2 = asyncio.ensure_future(plink.serve())
            ok = await plink.send_handoff(
                {"id": "w1", "p": 32, "nbytes": len(self.FRAME)},
                self.FRAME)
            assert ok
            assert len(pump.got) == 1
            meta, frame = pump.got[0]
            assert frame == self.FRAME  # byte-identical after rechunking
            assert meta["id"] == "w1" and meta["len"] == len(self.FRAME)
            # ...and the reassembled bytes still parse as a valid KV
            # frame (the corruption suite's contract, now on the wire).
            h = decode_kv_handoff(frame)
            assert h.p == 32 and h.request_id == "w1"
            assert len(self.FRAME) > 1024  # genuinely multi-chunk
            t1.cancel()
            t2.cancel()

        run_async(main())

    def test_corrupt_chunk_naks_then_retransmit_succeeds(self):
        async def main():
            a, b = memory_pair()
            pump = _MiniDecodePump(a)
            # Frame #2 on the wire is attempt 1's first chunk (after
            # begin); its last byte is chunk payload → CRC mismatch at
            # end → nak → attempt 2 retransmits clean.
            plink = _plink(_ManglingConnection(b, mangle_frame=2))
            t1 = asyncio.ensure_future(pump.run())
            t2 = asyncio.ensure_future(plink.serve())
            ok = await plink.send_handoff(
                {"id": "w2", "p": 32, "nbytes": len(self.FRAME)},
                self.FRAME)
            assert ok
            assert plink.sender.stats["retries"] == 1
            assert len(pump.got) == 1 and pump.got[0][1] == self.FRAME
            # the corrupt attempt was discarded whole, never surfaced
            assert pump.reasm.stats["partial_discards"] == 1
            t1.cancel()
            t2.cancel()

        run_async(main())

    def test_mid_transfer_disconnect_discards_partial(self):
        async def main():
            FAULTS.load({"disagg.net.drop_link": "drop_frame@once"})
            try:
                a, b = memory_pair()
                pump = _MiniDecodePump(a)
                plink = _plink(b)
                t1 = asyncio.ensure_future(pump.run())
                t2 = asyncio.ensure_future(plink.serve())
                ok = await plink.send_handoff(
                    {"id": "w3", "p": 32, "nbytes": len(self.FRAME)},
                    self.FRAME)
                assert not ok  # the cable was pulled mid-transfer
                await asyncio.wait_for(t1, 5)  # pump sees EOF and exits
                # ZERO partial adoptions: nothing reached the handoff
                # callback, and the partial buffer is discarded whole.
                assert pump.got == []
                assert pump.reasm.active == 1
                assert pump.reasm.abort_all() == 1
                assert pump.reasm.active == 0
                t2.cancel()
            finally:
                FAULTS.clear()

        run_async(main())

    def test_credit_window_backpressures_sender(self):
        async def main():
            a, b = memory_pair()
            pump = _MiniDecodePump(a)
            # Window of ~one chunk: every subsequent chunk must wait
            # for the receiver's credit grant — the stall that, in the
            # real topology, propagates into prefill admissions.
            plink = _plink(b, credit_mb=1024 / 2**20)
            t1 = asyncio.ensure_future(pump.run())
            t2 = asyncio.ensure_future(plink.serve())
            ok = await plink.send_handoff(
                {"id": "w4", "p": 32, "nbytes": len(self.FRAME)},
                self.FRAME)
            assert ok
            stats = plink.stats()
            assert stats["credit_stalls"] > 0
            assert stats["credit_stall_s"] >= 0
            t1.cancel()
            t2.cancel()

        run_async(main())

    def test_ack_timeout_retries_then_fails(self):
        async def main():
            a, b = memory_pair()
            pump = _MiniDecodePump(a, ack=False)  # reassembles, never acks
            plink = _plink(b, ack_timeout_s=0.2, max_retries=1)
            t1 = asyncio.ensure_future(pump.run())
            t2 = asyncio.ensure_future(plink.serve())
            ok = await plink.send_handoff(
                {"id": "w5", "p": 32, "nbytes": len(self.FRAME)},
                self.FRAME)
            assert not ok
            assert plink.sender.stats["retries"] == 1
            assert plink.sender.stats["failed"] == 1
            await asyncio.sleep(0.05)
            assert pump.fails and pump.fails[0]["id"] == "w5"
            t1.cancel()
            t2.cancel()

        run_async(main())

    def test_clock_handshake_measures_skew(self):
        async def main():
            a, b = memory_pair()
            dialer = HandoffLink(a)
            responder = HandoffLink(b)

            async def echo_skewed():
                while True:
                    msg = await responder.recv()
                    if msg is None:
                        return
                    h, _ = msg
                    if h.get("op") == LinkOp.CLOCK:
                        await responder.send(
                            {"op": LinkOp.CLOCK, "t0": h.get("t0"),
                             "t": time.monotonic() + 5.0})

            t = asyncio.ensure_future(echo_skewed())
            offset = await link_clock_handshake(dialer)
            assert 4.9 < offset < 5.1  # the deliberate +5s skew, found
            t.cancel()

        run_async(main())

    def test_send_recv_fault_seams(self):
        async def main():
            a, b = memory_pair()
            tx = HandoffLink(b)
            rx = HandoffLink(a)
            # egress drop: the armed message vanishes on the wire
            FAULTS.load({"disagg.net.send": "drop_frame@once"})
            try:
                await tx.send({"op": LinkOp.CREDIT, "n": 1})  # dropped
                await tx.send({"op": LinkOp.CREDIT, "n": 2})
                h, _ = await rx.recv()
                assert h["n"] == 2
            finally:
                FAULTS.clear()
            # ingress drop: delivered bytes, message discarded on recv
            FAULTS.load({"disagg.net.recv": "drop_frame@once"})
            try:
                await tx.send({"op": LinkOp.CREDIT, "n": 3})  # discarded
                await tx.send({"op": LinkOp.CREDIT, "n": 4})
                h, _ = await rx.recv()
                assert h["n"] == 4
            finally:
                FAULTS.clear()

        run_async(main())


class TestCreditGate:
    def test_acquire_blocks_until_grant(self):
        async def main():
            gate = CreditGate(10)
            await gate.acquire(10)  # window exhausted
            acquired = asyncio.Event()

            async def taker():
                await gate.acquire(5)
                acquired.set()

            t = asyncio.ensure_future(taker())
            await asyncio.sleep(0.02)
            assert not acquired.is_set()
            gate.grant(3)  # not enough yet
            await asyncio.sleep(0.02)
            assert not acquired.is_set()
            gate.grant(3)
            await asyncio.wait_for(acquired.wait(), 2)
            assert gate.stats["credit_stalls"] == 1
            assert gate.available == 1
            t.cancel()

        run_async(main())
