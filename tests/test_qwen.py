"""Qwen2 family: QKV attention biases through model, engine, checkpoint."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import forward, init_cache, init_params, preset
from symmetry_tpu.models.llama import config_from_hf, param_logical_axes


class TestQwenModel:
    def test_params_carry_biases(self):
        cfg = preset("tiny-qwen")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        assert params["layers"]["bq"].shape == (2, 64)
        assert params["layers"]["bk"].shape == (2, 32)
        axes = param_logical_axes(cfg)
        assert axes["layers"]["bq"] == ("layers", "heads")

    def test_bias_changes_output(self):
        """Nonzero biases must flow into the logits (guards against the
        bias add being silently dropped)."""
        cfg = preset("tiny-qwen")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        tokens = jnp.asarray([[7, 3, 9]], jnp.int32)
        base, _ = forward(params, cfg, tokens,
                          init_cache(cfg, 1, 8, jnp.float32))
        params["layers"]["bq"] = params["layers"]["bq"] + 0.5
        moved, _ = forward(params, cfg, tokens,
                           init_cache(cfg, 1, 8, jnp.float32))
        assert np.abs(np.asarray(base) - np.asarray(moved)).max() > 1e-4

    def test_engine_greedy_matches_reference(self):
        cfg = preset("tiny-qwen")
        params = init_params(cfg, jax.random.key(1), jnp.float32)
        # give biases real values so the path is actually exercised
        for b in ("bq", "bk", "bv"):
            params["layers"][b] = jax.random.normal(
                jax.random.key(hash(b) % 1000),
                params["layers"][b].shape) * 0.1

        cache = init_cache(cfg, 1, 64, jnp.float32)
        prompt = list(b"qwen bias test")
        logits, cache = forward(params, cfg,
                                jnp.asarray([prompt], jnp.int32), cache)
        want = [int(jnp.argmax(logits[0, -1]))]
        last = jnp.asarray([want[-1]], jnp.int32)
        for _ in range(5):
            logits, cache = forward(params, cfg, last[:, None], cache)
            want.append(int(jnp.argmax(logits[0, 0])))
            last = jnp.asarray([want[-1]], jnp.int32)

        eng = InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                              max_seq_len=64, prefill_buckets=(16,),
                              cache_dtype=jnp.float32)
        got = [eng.prefill_and_insert(0, prompt, SamplingParams())]
        for _ in range(5):
            got.append(int(eng.decode_step()[0]))
        assert got == want

    def test_config_from_hf_qwen(self):
        cfg = config_from_hf({
            "architectures": ["Qwen2ForCausalLM"],
            "vocab_size": 152064, "hidden_size": 3584,
            "num_hidden_layers": 28, "num_attention_heads": 28,
            "num_key_value_heads": 4, "intermediate_size": 18944,
            "rope_theta": 1000000.0, "rms_norm_eps": 1e-6,
        })
        assert cfg.attention_bias
        # llama config stays bias-free
        cfg2 = config_from_hf({
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 128256, "hidden_size": 4096,
            "num_hidden_layers": 32, "num_attention_heads": 32,
            "intermediate_size": 14336,
        })
        assert not cfg2.attention_bias

    def test_checkpoint_roundtrip(self, tmp_path):
        pytest.importorskip("safetensors")
        from symmetry_tpu.engine.weights import load_checkpoint, save_checkpoint

        cfg = preset("tiny-qwen")
        params = init_params(cfg, jax.random.key(2), jnp.float32)
        for b in ("bq", "bk", "bv"):
            params["layers"][b] = jax.random.normal(
                jax.random.key(1), params["layers"][b].shape) * 0.1
        path = str(tmp_path / "qwen-ckpt")
        save_checkpoint(path, params, cfg)
        loaded, loaded_cfg = load_checkpoint(path, dtype=jnp.float32)
        assert loaded_cfg.attention_bias
        tokens = jnp.asarray([[5, 1, 8, 2]], jnp.int32)
        want, _ = forward(params, cfg, tokens,
                          init_cache(cfg, 1, 8, jnp.float32))
        got, _ = forward(loaded, loaded_cfg, tokens,
                         init_cache(cfg, 1, 8, jnp.float32))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_vestigial_sliding_window_ignored(self):
        """Real qwen2 configs ship sliding_window alongside
        use_sliding_window: false — honoring it would silently disable
        every fast attention path."""
        cfg = config_from_hf({
            "architectures": ["Qwen2ForCausalLM"],
            "vocab_size": 152064, "hidden_size": 3584,
            "num_hidden_layers": 28, "num_attention_heads": 28,
            "num_key_value_heads": 4, "intermediate_size": 18944,
            "sliding_window": 131072, "use_sliding_window": False,
        })
        assert cfg.sliding_window is None
        # an actually-enabled window is preserved (mistral v0.1 shape)
        cfg2 = config_from_hf({
            "architectures": ["MistralForCausalLM"],
            "vocab_size": 32000, "hidden_size": 4096,
            "num_hidden_layers": 32, "num_attention_heads": 32,
            "num_key_value_heads": 8, "intermediate_size": 14336,
            "sliding_window": 4096,
        })
        assert cfg2.sliding_window == 4096

    def test_moe_config_keeps_attention_bias(self):
        cfg = config_from_hf({
            "architectures": ["MixtralForCausalLM"],
            "vocab_size": 32000, "hidden_size": 4096,
            "num_hidden_layers": 32, "num_attention_heads": 32,
            "num_key_value_heads": 8, "intermediate_size": 14336,
            "num_local_experts": 8, "attention_bias": True,
        })
        assert cfg.attention_bias and cfg.num_experts == 8
