"""Transports + secure peer channel."""

import asyncio

import pytest

from symmetry_tpu.identity import HandshakeError, Identity
from symmetry_tpu.network.peer import Peer
from symmetry_tpu.protocol.keys import MessageKey
from symmetry_tpu.transport import MemoryTransport, TcpTransport, memory_pair


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_memory_pair_duplex():
    async def main():
        a, b = memory_pair()
        await a.send(b"hello")
        await b.send(b"world")
        assert await b.recv() == b"hello"
        assert await a.recv() == b"world"
        await a.close()
        assert await b.recv() is None

    run(main())


def test_memory_transport_dial_listen():
    async def main():
        hub = MemoryTransport()
        got = asyncio.Queue()

        async def handler(conn):
            got.put_nowait(await conn.recv())

        await hub.listen("mem://srv", handler)
        conn = await hub.dial("mem://srv")
        await conn.send(b"ping")
        assert await asyncio.wait_for(got.get(), 2) == b"ping"
        with pytest.raises(ConnectionRefusedError):
            await hub.dial("mem://nobody")

    run(main())


def test_tcp_transport_roundtrip():
    async def main():
        t = TcpTransport()
        echoed = asyncio.Queue()

        async def handler(conn):
            while (frame := await conn.recv()) is not None:
                await conn.send(frame + b"!")
            echoed.put_nowait(True)

        listener = await t.listen("tcp://127.0.0.1:0", handler)
        conn = await t.dial(listener.address)
        await conn.send(b"abc")
        await conn.send(b"x" * 200_000)  # multi-read frame
        assert await conn.recv() == b"abc!"
        assert await conn.recv() == b"x" * 200_000 + b"!"
        await conn.close()
        await asyncio.wait_for(echoed.get(), 2)
        await listener.close()

    run(main())


def _handshake_pair(client_ident, server_ident, expected_server=None, expected_client=None):
    async def main():
        a, b = memory_pair()
        client_task = asyncio.create_task(
            Peer.connect(a, client_ident, initiator=True, expected_remote_key=expected_server)
        )
        server_task = asyncio.create_task(
            Peer.connect(b, server_ident, initiator=False, expected_remote_key=expected_client)
        )
        return await asyncio.gather(client_task, server_task)

    return run(main())


def test_secure_peer_mutual_auth_and_messages():
    ci, si = Identity.from_name("client"), Identity.from_name("server")
    cp, sp = _handshake_pair(ci, si, expected_server=si.public_key)
    # Both sides learned the authentic remote identity.
    assert cp.remote_public_key == si.public_key
    assert sp.remote_public_key == ci.public_key

    async def chat():
        await cp.send(MessageKey.INFERENCE, {"messages": []})
        msg = await sp.recv()
        assert msg.key == MessageKey.INFERENCE
        await sp.send(MessageKey.INFERENCE_ENDED, {"n": 1})
        msg2 = await cp.recv()
        assert msg2.key == MessageKey.INFERENCE_ENDED and msg2.data == {"n": 1}
        # Many messages in flight — framing keeps boundaries.
        for i in range(50):
            await cp.send(MessageKey.PING, i)
        for i in range(50):
            assert (await sp.recv()).data == i

    run(chat())


def test_secure_peer_rejects_wrong_server_key():
    # Unlike the reference (advisory verify, src/provider.ts:157-167) a key
    # mismatch must abort the connection.
    ci, si = Identity.from_name("client2"), Identity.from_name("server2")
    imposter = Identity.from_name("imposter")

    async def main():
        a, b = memory_pair()
        client = asyncio.create_task(
            Peer.connect(a, ci, initiator=True, expected_remote_key=imposter.public_key)
        )
        server = asyncio.create_task(Peer.connect(b, si, initiator=False))
        with pytest.raises(HandshakeError):
            await client
        server.cancel()

    run(main())


def test_wire_is_actually_encrypted():
    # Sniff the raw frames between the peers: plaintext must not appear.
    ci, si = Identity.from_name("c3"), Identity.from_name("s3")

    async def main():
        a, b = memory_pair()
        cp_t = asyncio.create_task(Peer.connect(a, ci, initiator=True))
        sp_t = asyncio.create_task(Peer.connect(b, si, initiator=False))
        cp, sp = await cp_t, await sp_t

        secret = "the quick brown fox"
        sniffed = []
        orig_send = a.send

        async def sniffing_send(frame):
            sniffed.append(frame)
            await orig_send(frame)

        a.send = sniffing_send
        await cp.send(MessageKey.INFERENCE, {"content": secret})
        msg = await sp.recv()
        assert msg.data["content"] == secret
        assert sniffed and all(secret.encode() not in f for f in sniffed)

    run(main())


def test_tampered_ciphertext_drops_peer():
    ci, si = Identity.from_name("c4"), Identity.from_name("s4")

    async def main():
        a, b = memory_pair()
        cp_t = asyncio.create_task(Peer.connect(a, ci, initiator=True))
        sp_t = asyncio.create_task(Peer.connect(b, si, initiator=False))
        cp, sp = await cp_t, await sp_t

        orig_send = a.send

        async def corrupting_send(frame):
            await orig_send(frame[:-1] + bytes([frame[-1] ^ 1]))

        a.send = corrupting_send
        await cp.send(MessageKey.PING)
        assert await sp.recv() is None  # tampering → peer dropped, not garbage

    run(main())
