"""Subprocess entry for the multi-host lockstep test (test_multihost.py).

Runs as N real OS processes joined via jax.distributed on the CPU backend:
rank 0 leads a CommandLoop (prefill, decode blocks, stop), workers follow.
Every rank prints its final per-slot decode tokens; the parent asserts all
ranks stayed in lockstep and match the single-process reference.
"""

import json
import os
import sys


def main() -> None:
    rank = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    port = sys.argv[3]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")

    from symmetry_tpu.parallel.multihost import (
        CMD_DECODE, CMD_PREFILL, CommandLoop, MultihostEngine,
        init_distributed,
    )

    init_distributed(f"127.0.0.1:{port}", nprocs, rank)

    import jax.numpy as jnp
    import numpy as np

    from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, preset

    # Identical replicated engine on every process (same init seed).
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    engine = InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                             max_seq_len=64, prefill_buckets=(16,),
                             cache_dtype=jnp.float32, decode_block=2)
    loop = CommandLoop(engine, is_coordinator=rank == 0)

    collected: list[list[int]] = []
    if rank == 0:
        mh = MultihostEngine(loop)
        first = mh.prefill_and_insert(0, list(b"multi host"),
                                      SamplingParams(seed=7, temperature=0.5))
        collected.append([first])
        for _ in range(3):
            toks = mh.decode_steps()
            collected.append(np.asarray(toks)[:, 0].tolist())  # slot 0 tokens
        loop.stop()
    else:
        # Workers mirror; capture their own engine's view afterwards.
        orig_execute = loop._execute
        def record(cmd):
            out = orig_execute(cmd)
            if cmd.kind == CMD_PREFILL:
                collected.append([int(out)])
            elif cmd.kind == CMD_DECODE:
                collected.append(np.asarray(out)[:, 0].tolist())
            return out
        loop._execute = record
        loop.follow_forever()

    lengths = [engine.slot_length(s) for s in range(2)]
    print("RESULT " + json.dumps({"rank": rank, "tokens": collected,
                                  "lengths": lengths}), flush=True)


if __name__ == "__main__":
    main()
