"""Request-scoped distributed tracing (PR 5): clock reconciliation,
Perfetto export, flight recorder, trace propagation, structured logs.

Layout mirrors the layer being tested:

  - Histogram snapshot consistency (the to_dict/mean race fix);
  - clock_handshake_offset + the tpu_native per-stage attribution with a
    MEASURED offset (the negative-span clamp's replacement), including a
    full fake-host pipe round trip with a deliberately skewed host clock;
  - export_perfetto schema + cross-component reconciliation;
  - FlightRecorder dump/window/rate-limit;
  - scheduler span/counter rings on a fake engine (trace_id propagation);
  - EngineHost clock/trace op handlers;
  - JSON log mode stamping trace_id/request_id from log_context;
  - (crypto-gated) echo-backend e2e: client → provider trace op → merged
    Perfetto export with >= 3 components on one reconciled clock.
"""

import asyncio
import json
import sys
import threading
import time
from types import SimpleNamespace

import pytest

from symmetry_tpu.engine.host import EngineHost
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.utils.trace import (
    FlightRecorder,
    Histogram,
    Tracer,
    clock_handshake_offset,
    export_perfetto,
    new_trace_id,
)


class TestHistogramSnapshot:
    def test_to_dict_is_consistent_under_concurrent_observe(self):
        """count/total/min/max/reservoir are mutated together under the
        lock; a snapshot must read them together too. Every observation
        is exactly 1.0, so ANY consistent snapshot has mean == 1.0 —
        the old unlocked reads could pair a fresh total with a stale
        count and report a mean no prefix of the stream ever had."""
        h = Histogram()
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                h.observe(1.0)

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            last_count = 0
            for _ in range(300):
                d = h.to_dict()
                if d["count"]:
                    assert d["mean"] == 1.0
                    assert d["min"] == d["max"] == 1.0
                    assert d["p50"] == 1.0
                assert d["count"] >= last_count  # monotone snapshots
                last_count = d["count"]
                assert h.mean in (None, 1.0)
        finally:
            stop.set()
            for t in threads:
                t.join()

    def test_percentile_consistent_with_snapshot(self):
        h = Histogram()
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        d = h.to_dict()
        assert d["count"] == 3 and d["p50"] == 0.2
        assert h.mean == pytest.approx(0.2)


class TestClockHandshake:
    def test_midpoint_recovers_offset(self):
        # Symmetric RTT: the midpoint recovers the offset exactly.
        off = 5.0
        samples = [(t, (t + 0.001) + off, t + 0.002)
                   for t in (10.0, 11.0, 12.0)]
        assert clock_handshake_offset(samples) == pytest.approx(off)

    def test_min_rtt_sample_wins(self):
        # A slow, asymmetric round trip would estimate badly; the tight
        # sample must win regardless of order.
        good = (10.0, 10.0005 + 2.0, 10.001)
        bad = (11.0, 11.9 + 2.0, 12.0)  # 1s rtt, reply-heavy
        assert clock_handshake_offset([bad, good]) == pytest.approx(
            2.0, abs=1e-6)
        assert clock_handshake_offset([]) == 0.0

    def test_negative_offset(self):
        samples = [(100.0, 100.001 - 7.5, 100.002)]
        assert clock_handshake_offset(samples) == pytest.approx(-7.5)


def make_tpu_backend():
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend

    cfg = ConfigManager(config={
        "name": "t", "public": False, "serverKey": "00" * 32,
        "modelName": "tiny:test", "apiProvider": "tpu_native",
        "tpu": {"model_preset": "tiny", "max_batch_size": 2,
                "max_seq_len": 64, "prefill_buckets": [16]},
    })
    return TpuNativeBackend(cfg)


class TestStageOffsetReconciliation:
    """Regression for the tpu_native negative-span clamp: host stamps are
    now mapped through the MEASURED clock offset before differencing."""

    def test_offsets_applied_not_clamped(self):
        be = make_tpu_backend()
        # Host clock runs 5 s BEHIND the provider: every host stamp is
        # 5 s smaller than the provider stamps bracketing it, so naive
        # differencing makes pipe_in ≈ -5 s — the case the old code
        # clamped to zero (hiding the whole leg).
        be._clock_offset = -5.0
        t_recv = 1000.0
        t_submit = 1000.010
        host = -5.0  # host clock = provider clock + offset
        stamps = {"recv": round(1000.020 + host, 4),
                  "picked": round(1000.050 + host, 4),
                  "first": round(1000.200 + host, 4),
                  "out": round(1000.210 + host, 4)}
        be._observe_stages(t_recv, t_submit, stamps)
        get = lambda n: be.stage_hists[n].to_dict()  # noqa: E731
        assert get("submit")["mean"] == pytest.approx(0.010, abs=1e-6)
        # The leg that used to clamp: recv lands AFTER submit once the
        # offset is applied.
        assert get("pipe_in")["mean"] == pytest.approx(0.010, abs=1e-6)
        assert get("queue")["mean"] == pytest.approx(0.030, abs=1e-6)
        assert get("prefill")["mean"] == pytest.approx(0.150, abs=1e-6)
        assert get("emit")["mean"] == pytest.approx(0.010, abs=1e-6)
        # relay = real now - reconciled out: meaningless against these
        # fabricated stamps; just assert it was recorded (not dropped).
        assert get("relay")["count"] == 1

    def test_true_negative_span_not_hidden(self):
        """A genuinely mis-ordered stamp pair must surface as a negative
        observation — the clamp used to silently zero it."""
        be = make_tpu_backend()
        be._clock_offset = 0.0
        stamps = {"recv": 999.0, "picked": 999.0, "first": 999.0,
                  "out": 999.0}
        be._observe_stages(1000.0, 1000.5, stamps)
        d = be.stage_hists["pipe_in"].to_dict()
        assert d["count"] == 1
        assert d["mean"] == pytest.approx(-1.5)


FAKE_HOST = r'''
import json, sys, time
SKEW = float(sys.argv[1])

def mono():
    return time.monotonic() + SKEW

def write(obj):
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()

write({"op": "ready", "model": "fake"})
for line in sys.stdin:
    line = line.strip()
    if not line:
        continue
    msg = json.loads(line)
    op = msg.get("op")
    if op == "clock":
        write({"op": "clock", "t0": msg.get("t0"), "t": mono()})
    elif op == "submit":
        rid = msg["id"]
        t = mono()
        write({"op": "event", "id": rid, "text": "hi", "tokens": 1,
               "tokens_new": 1, "ttft_s": 0.001,
               "t": {"recv": round(t, 4), "picked": round(t + 0.001, 4),
                     "first": round(t + 0.002, 4),
                     "out": round(t + 0.003, 4)}})
        write({"op": "event", "id": rid, "text": "", "tokens": 2,
               "tokens_new": 0, "done": True, "finish_reason": "stop"})
    elif op == "trace":
        t = mono()
        write({"op": "trace", "clock": t, "components": [
            {"name": "host", "clock_offset_s": 0.0, "counters": [],
             "spans": [{"name": "host_submit", "start": t - 0.5,
                        "duration_s": 0.001, "request_id": "r1",
                        "trace_id": "tid-1"}]},
            {"name": "scheduler", "clock_offset_s": 0.0,
             "counters": [{"t": t - 0.4, "name": "occupancy", "value": 1}],
             "spans": [{"name": "prefill", "start": t - 0.4,
                        "duration_s": 0.1, "request_id": "r1",
                        "trace_id": "tid-1"}]}]})
    elif op == "shutdown":
        break
'''


class TestFakeHostPipe:
    """Process-isolation protocol against a scripted host whose clock is
    deliberately skewed: the startup handshake must MEASURE the skew, the
    per-stage attribution must reconcile through it (no clamping), and
    trace_components must stamp it onto the host/scheduler components."""

    SKEW = -5.0  # host monotonic runs 5 s behind the provider's

    @pytest.fixture()
    def backend(self, tmp_path, monkeypatch):
        script = tmp_path / "fake_host.py"
        script.write_text(FAKE_HOST)
        real_exec = asyncio.create_subprocess_exec

        async def fake_exec(*_args, **kw):
            return await real_exec(sys.executable, str(script),
                                   str(self.SKEW), **kw)

        monkeypatch.setattr(asyncio, "create_subprocess_exec", fake_exec)
        return make_tpu_backend()

    def test_handshake_stages_and_trace(self, backend):
        from symmetry_tpu.provider.backends.base import InferenceRequest

        async def main():
            await backend.start()
            # 1. The handshake measured the scripted skew (pipe RTT on
            # loopback bounds the error well under 50 ms).
            assert backend._clock_offset == pytest.approx(self.SKEW,
                                                          abs=0.05)
            # 2. Stream one request: the first event's host stamps are
            # ~5 s "in the past"; unreconciled, pipe_in/queue/prefill
            # would be hugely negative (old code: clamped to 0).
            chunks = []
            async for ch in backend.stream(InferenceRequest(
                    messages=[{"role": "user", "content": "x"}],
                    max_tokens=4, trace_id="tid-1")):
                chunks.append(ch)
            assert any(ch.done for ch in chunks)
            for stage in ("pipe_in", "queue", "prefill", "emit"):
                d = backend.stage_hists[stage].to_dict()
                assert d["count"] == 1
                # Reconciled: small positive (scripted micro-gaps plus
                # handshake residual), nowhere near -SKEW or a clamp.
                assert -0.1 < d["mean"] < 1.0, (stage, d)
            # 3. trace_components applies the measured offset to every
            # host-side component, so the merged export reconciles.
            comps = await backend.trace_components()
            names = {c["name"] for c in comps}
            assert names == {"host", "scheduler"}
            for c in comps:
                assert c["clock_offset_s"] == pytest.approx(self.SKEW,
                                                            abs=0.05)
            perfetto = export_perfetto(comps)
            xs = [e for e in perfetto["traceEvents"] if e["ph"] == "X"]
            assert xs and all(e["ts"] >= 0 for e in xs)
            assert {e["args"]["trace_id"] for e in xs} == {"tid-1"}
            await backend.stop()

        asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(main(), 60))


class TestPerfettoExport:
    def test_schema_and_reconciliation(self):
        # Two components, the second's clock 10 s ahead: a span that
        # STARTED LATER in real time but carries a bigger raw stamp must
        # still order correctly after reconciliation.
        provider = {"name": "provider", "clock_offset_s": 0.0,
                    "counters": [],
                    "spans": [{"name": "inference", "start": 100.0,
                               "duration_s": 1.0, "request_id": "r1",
                               "trace_id": "t1"}]}
        host = {"name": "host", "clock_offset_s": 10.0,
                "counters": [{"t": 110.3, "name": "occupancy", "value": 2}],
                "spans": [{"name": "prefill", "start": 110.2,
                           "duration_s": 0.5, "request_id": "r1",
                           "trace_id": "t1"}]}
        out = export_perfetto([provider, host])
        assert out["displayTimeUnit"] == "ms"
        events = out["traceEvents"]
        procs = {e["args"]["name"]: e["pid"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert set(procs) == {"provider", "host"}
        xs = {e["name"]: e for e in events if e["ph"] == "X"}
        assert xs["inference"]["ts"] == 0.0          # the earliest stamp
        assert xs["inference"]["dur"] == 1_000_000.0
        assert xs["prefill"]["ts"] == pytest.approx(200_000.0)  # +0.2 s
        assert xs["prefill"]["pid"] == procs["host"]
        cs = [e for e in events if e["ph"] == "C"]
        assert cs[0]["args"] == {"occupancy": 2}
        assert cs[0]["ts"] == pytest.approx(300_000.0)
        # every ts non-negative on the reconciled clock
        assert all(e["ts"] >= 0 for e in events if e["ph"] in "XC")

    def test_thread_rows_per_request(self):
        comp = {"name": "c", "clock_offset_s": 0.0, "counters": [],
                "spans": [
                    {"name": "a", "start": 1.0, "duration_s": 0.1,
                     "request_id": "r1", "trace_id": ""},
                    {"name": "b", "start": 1.2, "duration_s": 0.1,
                     "request_id": "r2", "trace_id": ""},
                    {"name": "c", "start": 1.4, "duration_s": 0.1,
                     "request_id": "r1", "trace_id": ""}]}
        events = export_perfetto([comp])["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        by_name = {e["name"]: e["tid"] for e in xs}
        assert by_name["a"] == by_name["c"] != by_name["b"]
        thread_names = {e["args"]["name"] for e in events
                        if e["ph"] == "M" and e["name"] == "thread_name"}
        assert thread_names == {"r1", "r2"}

    def test_empty(self):
        out = export_perfetto([])
        assert out["traceEvents"] == []
        assert json.loads(json.dumps(out)) == out


class TestFlightRecorder:
    def comps(self, now):
        return [{"name": "provider", "clock_offset_s": 0.0, "counters": [],
                 "spans": [
                     {"name": "old", "start": now - 120.0,
                      "duration_s": 0.1, "request_id": "", "trace_id": ""},
                     {"name": "recent", "start": now - 2.0,
                      "duration_s": 0.5, "request_id": "r", "trace_id": "t"},
                 ]}]

    def test_dump_is_loadable_and_windowed(self, tmp_path):
        fr = FlightRecorder(str(tmp_path), window_s=30.0)
        now = time.monotonic()
        path = fr.dump("slo", self.comps(now), stats={"requests": 3},
                       now=now)
        with open(path) as fh:
            payload = json.load(fh)
        assert payload["reason"] == "slo"
        assert payload["stats"] == {"requests": 3}
        names = [e["name"] for e in payload["trace"]["traceEvents"]
                 if e["ph"] == "X"]
        assert names == ["recent"]  # the 2-minute-old span fell outside

    def test_rate_limit(self, tmp_path):
        fr = FlightRecorder(str(tmp_path), min_interval_s=3600.0)
        assert fr.should_dump()
        assert not fr.should_dump()  # the first claim holds the slot

    def test_skewed_component_windowing(self, tmp_path):
        # A host-clock span 5 s in the "future" raw but recent reconciled
        # must survive the window filter (and vice versa).
        fr = FlightRecorder(str(tmp_path), window_s=10.0)
        now = time.monotonic()
        comp = {"name": "host", "clock_offset_s": 5.0, "counters": [],
                "spans": [{"name": "recent", "start": now + 4.0,
                           "duration_s": 0.1, "request_id": "",
                           "trace_id": ""},       # reconciled: now - 1
                          {"name": "stale", "start": now - 55.0,
                           "duration_s": 0.1, "request_id": "",
                           "trace_id": ""}]}     # reconciled: now - 60
        path = fr.dump("sigusr2", [comp], now=now)
        with open(path) as fh:
            names = [e["name"] for e in
                     json.load(fh)["trace"]["traceEvents"]
                     if e["ph"] == "X"]
        assert names == ["recent"]


class SpanFakeEngine:
    """Minimal scheduler-facing engine (cf. test_scheduler_emit)."""

    def __init__(self):
        from symmetry_tpu.engine.tokenizer import ByteTokenizer

        self.max_slots = 4
        self.decode_block = 4
        self.slot_capacity = 4096
        self.tokenizer = ByteTokenizer()
        self.prefill_buckets = (16,)

    def bucket_for(self, n):
        return 16

    def prefill_and_insert(self, slot, ids, sampling):
        return ord("A")

    def prefill_and_insert_many(self, group):
        return [ord("A")] * len(group)

    def release_slot(self, slot):
        pass

    def slot_length(self, slot):
        return 0


class TestSchedulerSpans:
    def make(self):
        from symmetry_tpu.engine.scheduler import Scheduler

        batches = []
        return Scheduler(SpanFakeEngine(), emit_batch=batches.append)

    def submit_one(self, sched, rid="req-1", tid="trace-1"):
        from symmetry_tpu.engine.engine import SamplingParams
        from symmetry_tpu.engine.scheduler import GenRequest

        sched.submit(GenRequest(
            prompt_ids=list(b"hello"), sampling=SamplingParams(),
            max_new_tokens=64, emit=lambda ev: None, id=rid,
            trace_id=tid))

    def test_admission_spans_carry_trace_id(self):
        sched = self.make()
        self.submit_one(sched)
        sched._admit_new()
        spans = {s["name"]: s for s in sched.tracer.export()}
        assert "prefill_dispatch" in spans
        for name in ("queue", "prefill"):
            assert spans[name]["request_id"] == "req-1"
            assert spans[name]["trace_id"] == "trace-1"
        assert spans["queue"]["start"] <= spans["prefill"]["start"]

    def test_block_spans_and_counters(self):
        import numpy as np

        sched = self.make()
        self.submit_one(sched)
        sched._admit_new()
        toks = np.full((4, 4), ord("x"), dtype=np.int64)
        t_disp = time.monotonic() - 0.01
        sched._process_block(toks, dict(sched._slots),
                             dispatched_at=t_disp)
        spans = [s for s in sched.tracer.export()
                 if s["name"] == "decode_block"]
        assert len(spans) == 1
        assert spans[0]["start"] == t_disp
        assert spans[0]["steps"] == 4 and spans[0]["slots"] == 1
        counters = {c["name"] for c in sched.tracer.export_counters()}
        assert {"occupancy", "queue_depth"} <= counters

    def test_generate_span_on_finish(self):
        import numpy as np

        sched = self.make()
        self.submit_one(sched)
        sched._admit_new()
        eos = sched.engine.tokenizer.EOS
        toks = np.full((4, 4), eos, dtype=np.int64)
        sched._process_block(toks, dict(sched._slots))
        gen = [s for s in sched.tracer.export() if s["name"] == "generate"]
        assert len(gen) == 1
        assert gen[0]["trace_id"] == "trace-1"
        assert gen[0]["finish"] == "stop"

    def test_disabled_tracer_records_nothing(self):
        import numpy as np

        sched = self.make()
        sched.tracer.enabled = False
        self.submit_one(sched)
        sched._admit_new()
        toks = np.full((4, 4), ord("x"), dtype=np.int64)
        sched._process_block(toks, dict(sched._slots),
                             dispatched_at=time.monotonic())
        assert sched.tracer.export() == []
        assert sched.tracer.export_counters() == []
        assert sched.trace_export()["spans"] == []


class TestHostTraceOps:
    def test_clock_echo(self, capsys):
        host = EngineHost(config=None)
        t_before = time.monotonic()
        host._handle_clock({"op": "clock", "t0": 123.456})
        reply = json.loads(capsys.readouterr().out.strip())
        assert reply["op"] == "clock" and reply["t0"] == 123.456
        assert t_before <= reply["t"] <= time.monotonic()

    def test_trace_op_ships_host_and_scheduler_rings(self, capsys):
        host = EngineHost(config=None)
        host.tracer.record("host_submit", 1.0, 0.01, request_id="r",
                           trace_id="t")
        sched_tracer = Tracer()
        sched_tracer.record("prefill", 2.0, 0.1)
        host._scheduler = SimpleNamespace(
            trace_export=lambda: sched_tracer.component("scheduler"))
        host._handle_trace()
        frame = json.loads(capsys.readouterr().out.strip())
        assert frame["op"] == "trace"
        names = [c["name"] for c in frame["components"]]
        assert names == ["host", "scheduler"]
        assert frame["components"][0]["spans"][0]["trace_id"] == "t"
        assert frame["components"][1]["spans"][0]["name"] == "prefill"

    def test_submit_threads_trace_id(self, capsys):
        host = EngineHost(config=None)
        seen = []
        host._scheduler = SimpleNamespace(submit=seen.append)
        host._engine = SimpleNamespace(tokenizer=SimpleNamespace(
            apply_chat_template=lambda msgs: [1, 2, 3]))
        host._submit({"op": "submit", "id": "r9", "trace": "tid-9",
                      "messages": [{"role": "user", "content": "x"}],
                      "max_new": 8})
        assert len(seen) == 1
        assert seen[0].trace_id == "tid-9"
        spans = host.tracer.export()
        assert spans and spans[-1]["name"] == "host_submit"
        assert spans[-1]["trace_id"] == "tid-9"
        assert spans[-1]["request_id"] == "r9"


class TestJsonLogging:
    def test_json_records_carry_trace_context(self, capsys):
        from symmetry_tpu.utils.logging import log_context, logger

        logger.set_json_mode(True)
        try:
            with log_context(trace_id="tr-1", request_id="rq-1"):
                logger.info("hello", "world")
            logger.info("outside")
        finally:
            logger.set_json_mode(False)
        lines = [json.loads(line) for line in
                 capsys.readouterr().err.strip().splitlines()]
        assert lines[0]["msg"] == "hello world"
        assert lines[0]["level"] == "info"
        assert lines[0]["trace_id"] == "tr-1"
        assert lines[0]["request_id"] == "rq-1"
        assert "trace_id" not in lines[1]  # context does not leak

    def test_nested_context_overrides_and_restores(self, capsys):
        from symmetry_tpu.utils.logging import log_context, logger

        logger.set_json_mode(True)
        try:
            with log_context(trace_id="outer"):
                with log_context(trace_id="inner", request_id="r"):
                    logger.warning("deep")
                logger.warning("shallow")
        finally:
            logger.set_json_mode(False)
        lines = [json.loads(line) for line in
                 capsys.readouterr().err.strip().splitlines()]
        assert lines[0]["trace_id"] == "inner"
        assert lines[0]["request_id"] == "r"
        assert lines[1]["trace_id"] == "outer"
        assert "request_id" not in lines[1]


class TestEchoTraceE2E:
    """Full client → server → provider (echo backend) path on the memory
    transport: trace propagation, the `trace` wire op, the merged
    Perfetto export, and the flight-recorder SLO trigger. Skips where the
    crypto stack isn't installed (same dependency as every peer test)."""

    def run_flow(self, tmp_path, slo_e2e_s=None):
        pytest.importorskip("cryptography")
        from symmetry_tpu.client.client import SymmetryClient
        from symmetry_tpu.identity import Identity
        from symmetry_tpu.provider.provider import SymmetryProvider
        from symmetry_tpu.server.broker import SymmetryServer
        from symmetry_tpu.transport.memory import MemoryTransport

        async def main():
            hub = MemoryTransport()
            server_ident = Identity.from_name("obs-server")
            server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
            await server.start("mem://server")
            cfg = ConfigManager(config={
                "name": "obs-prov", "public": True,
                "serverKey": server_ident.public_hex,
                "modelName": "echo:obs", "apiProvider": "echo",
                "dataCollectionEnabled": False,
                "flightRecorder": {"dir": str(tmp_path / "flight"),
                                   "minIntervalS": 0.0,
                                   **({"sloE2eS": slo_e2e_s}
                                      if slo_e2e_s is not None else {})},
            })
            provider = SymmetryProvider(
                cfg, transport=hub, identity=Identity.from_name("obs-prov"),
                server_address="mem://server")
            await provider.start("mem://obs-prov")
            await provider.wait_registered()
            client = SymmetryClient(Identity.from_name("obs-cli"), hub)
            details = await client.request_provider(
                "mem://server", server_ident.public_key, "echo:obs")
            session = await client.connect(details)
            trace_id = new_trace_id()
            try:
                text = "".join([d async for d in session.chat(
                    [{"role": "user", "content": "one two three"}],
                    trace_id=trace_id)])
                assert text == "one two three"
                assert session.clock_offset is not None  # tMono handshake
                perfetto = await client.export_trace(session)
                # Let the SLO-triggered dump task (spawned, not awaited
                # by the stream) finish before teardown.
                for _ in range(100):
                    if list((tmp_path / "flight").glob("*.json")):
                        break
                    await asyncio.sleep(0.02)
            finally:
                await session.close()
                await provider.stop()
                await server.stop()
            return perfetto, trace_id

        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(main(), 120))

    def test_trace_round_trip_three_components(self, tmp_path):
        perfetto, trace_id = self.run_flow(tmp_path)
        events = perfetto["traceEvents"]
        comp_by_pid = {e["pid"]: e["args"]["name"] for e in events
                       if e["ph"] == "M" and e["name"] == "process_name"}
        span_comps = {comp_by_pid[e["pid"]] for e in events
                      if e["ph"] == "X"}
        assert {"client", "provider", "echo"} <= span_comps
        traced = {comp_by_pid[e["pid"]] for e in events
                  if e["ph"] == "X"
                  and e.get("args", {}).get("trace_id") == trace_id}
        assert {"client", "provider", "echo"} <= traced
        assert all(e["ts"] >= 0 for e in events if e["ph"] in "XC")
        # valid Chrome-trace JSON end to end
        assert json.loads(json.dumps(perfetto)) == perfetto

    def test_tpu_native_inproc_scheduler_on_timeline(self):
        """One request through the REAL engine (tiny model, inproc): the
        client's trace id must key scheduler spans (queue/prefill/
        generate) in the merged export — the engine side of the
        end-to-end acceptance path (the host hop is covered by
        TestFakeHostPipe with a skewed clock)."""
        pytest.importorskip("cryptography")
        from symmetry_tpu.client.client import SymmetryClient
        from symmetry_tpu.identity import Identity
        from symmetry_tpu.provider.provider import SymmetryProvider
        from symmetry_tpu.server.broker import SymmetryServer
        from symmetry_tpu.transport.memory import MemoryTransport

        async def main():
            hub = MemoryTransport()
            server_ident = Identity.from_name("obs-tpu-server")
            server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
            await server.start("mem://server")
            cfg = ConfigManager(config={
                "name": "obs-tpu-prov", "public": True,
                "serverKey": server_ident.public_hex,
                "modelName": "tiny:test", "apiProvider": "tpu_native",
                "dataCollectionEnabled": False,
                "flightRecorder": {"enabled": False},
                "tpu": {"model_preset": "tiny", "dtype": "float32",
                        "max_batch_size": 2, "max_seq_len": 128,
                        "prefill_buckets": [32],
                        "engine_isolation": "inproc"},
            })
            provider = SymmetryProvider(
                cfg, transport=hub,
                identity=Identity.from_name("obs-tpu-prov"),
                server_address="mem://server")
            await provider.start("mem://obs-tpu-prov")
            await provider.wait_registered()
            client = SymmetryClient(Identity.from_name("obs-tpu-cli"), hub)
            details = await client.request_provider(
                "mem://server", server_ident.public_key, "tiny:test")
            session = await client.connect(details)
            trace_id = new_trace_id()
            try:
                async for _ in session.chat(
                        [{"role": "user", "content": "hi"}],
                        max_tokens=8, trace_id=trace_id):
                    pass
                comps = await session.trace_components()
            finally:
                await session.close()
                await provider.stop()
                await server.stop()
            return comps, trace_id

        comps, trace_id = asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(main(), 300))
        by_name = {c["name"]: c for c in comps}
        assert {"client", "provider", "scheduler"} <= set(by_name)
        sched_spans = {s["name"] for s in by_name["scheduler"]["spans"]
                       if s.get("trace_id") == trace_id}
        assert {"queue", "prefill", "generate"} <= sched_spans
        events = export_perfetto(comps)["traceEvents"]
        assert all(e["ts"] >= 0 for e in events if e["ph"] in "XC")

    def test_flight_recorder_slo_trigger_dump_loads(self, tmp_path):
        # SLO of 0 s: the very first completed request breaches it.
        self.run_flow(tmp_path, slo_e2e_s=1e-9)
        dumps = list((tmp_path / "flight").glob("flight_*_slo.json"))
        assert dumps, "SLO breach produced no flight-recorder dump"
        with open(dumps[0]) as fh:
            payload = json.load(fh)
        assert payload["reason"] == "slo"
        assert payload["stats"].get("requests", 0) >= 1
        xs = [e for e in payload["trace"]["traceEvents"]
              if e["ph"] == "X"]
        assert xs, "dump carries no spans"
