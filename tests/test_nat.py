"""NAT traversal: UDP hole punching (network/natpunch.py) and the
server-spliced relay fallback (network/relay.py).

The reference gets both legs from hyperdht (holepunching + relaying,
SURVEY §2.2). No real NAT exists on loopback (and this box has no
nftables to build one), so these tests verify the full traversal
CHOREOGRAPHY — reflexive-address learning, invite delivery, simultaneous
punch bursts, dialing through the punched path, and ciphertext-only
relay splicing — over real UDP/memory transports.
"""

import asyncio

import pytest

from symmetry_tpu.client.client import ClientError, ProviderDetails, SymmetryClient
from symmetry_tpu.identity import Identity
from symmetry_tpu.network.natpunch import (
    PunchRendezvous,
    punch_dial,
    unwrap_raw,
    wrap_raw,
)
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.server.broker import SymmetryServer
from symmetry_tpu.transport.memory import MemoryTransport


def run(coro, timeout=60):
    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(coro, timeout))


class TestRawFraming:
    def test_roundtrip(self):
        assert unwrap_raw(wrap_raw(b"hello")) == b"hello"

    def test_rejects_garbage(self):
        assert unwrap_raw(b"\xff\xff\xff\xffAAAA") is None
        assert unwrap_raw(b"") is None


def _udp_available():
    try:
        from symmetry_tpu.transport.udp import load_library

        load_library()
        return True
    except Exception:  # noqa: BLE001
        return False


@pytest.mark.skipif(not _udp_available(), reason="udpstream lib unavailable")
class TestHolePunch:
    def test_punch_then_stream(self):
        """Full choreography: provider registers its reflexive address,
        client punches through the rendezvous, then opens a real
        udpstream connection on the punched path and exchanges frames."""
        async def main():
            from symmetry_tpu.network.natpunch import ProviderPuncher
            from symmetry_tpu.transport.udp import UdpTransport

            rdv = PunchRendezvous()
            await rdv.start("127.0.0.1", 0)

            got = asyncio.Queue()

            async def echo_handler(conn):
                frame = await conn.recv()
                await got.put(frame)
                await conn.send(b"pong:" + (frame or b""))

            ident = Identity.from_name("punch-prov")
            provider_t = UdpTransport()
            listener = await provider_t.listen("udp://127.0.0.1:0",
                                               echo_handler)
            puncher = ProviderPuncher(listener.raw_channel(),
                                      ("127.0.0.1", rdv.port), ident)
            puncher.start()
            await asyncio.sleep(0.3)  # registration datagram lands

            client_t = UdpTransport()
            address = await punch_dial(client_t, ("127.0.0.1", rdv.port),
                                       ident.public_hex)
            assert address == listener.address
            assert puncher.punched == 1  # the invite produced a burst

            conn = await client_t.dial(address)
            await conn.send(b"ping")
            assert await conn.recv() == b"pong:ping"
            await conn.close()

            # forged (unsigned) registration must NOT move the record
            import json as _json

            from symmetry_tpu.network.natpunch import wrap_raw

            evil = _json.dumps({"op": "register",
                                "key": ident.public_hex}).encode()
            rdv._on_datagram(wrap_raw(evil), ("10.9.9.9", 9999))
            assert rdv._registry[ident.public_hex][0][0] == "127.0.0.1"

            # and a REPLAYED (validly signed, old ts) register from a
            # different address must not move it either
            import time as _time

            from symmetry_tpu.network.natpunch import _register_sig_msg

            old_ts = rdv._last_ts[ident.public_hex]
            replay = _json.dumps({
                "op": "register", "key": ident.public_hex,
                "ts": old_ts,
                "sig": ident.sign(_register_sig_msg(
                    ident.public_hex, old_ts)).hex()}).encode()
            rdv._on_datagram(wrap_raw(replay), ("10.9.9.9", 9999))
            assert rdv._registry[ident.public_hex][0][0] == "127.0.0.1"

            await puncher.stop()
            await listener.close()
            await rdv.stop()

        run(main())

    def test_unknown_key_fails_fast(self):
        async def main():
            from symmetry_tpu.transport.udp import UdpTransport

            rdv = PunchRendezvous()
            await rdv.start("127.0.0.1", 0)
            with pytest.raises(ConnectionError, match="does not know"):
                await punch_dial(UdpTransport(), ("127.0.0.1", rdv.port),
                                 "nobody", timeout_s=3.0)
            await rdv.stop()

        run(main())


class TestRelayFallback:
    def test_chat_through_relay_when_direct_dial_fails(self):
        """Provider reachable ONLY via the server splice (its advertised
        address is bogus — the behind-NAT case): the chat must complete
        through the relay, with the provider's key still pinned end to
        end."""
        async def main():
            hub = MemoryTransport()
            server_ident = Identity.from_name("relay-server")
            server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
            await server.start("mem://server")

            cfg = ConfigManager(config={
                "name": "relay-prov", "public": True,
                "serverKey": server_ident.public_hex,
                "modelName": "tiny:relay", "apiProvider": "echo",
                "dataCollectionEnabled": False,
            })
            prov_ident = Identity.from_name("relay-prov")
            provider = SymmetryProvider(cfg, transport=hub,
                                        identity=prov_ident,
                                        server_address="mem://server")
            await provider.start("mem://relay-prov")
            await provider.wait_registered()

            client = SymmetryClient(Identity.from_name("relay-cli"), hub)
            details = await client.request_provider(
                "mem://server", server_ident.public_key, "tiny:relay")
            # Simulate NAT: the advertised address is undialable.
            details = ProviderDetails(
                peer_key=details.peer_key, address="mem://unreachable",
                model_name=details.model_name,
                session_token=details.session_token,
                session_id=details.session_id)

            session = await client.connect(
                details,
                relay_via=("mem://server", server_ident.public_key))
            text = await session.chat_text(
                [{"role": "user", "content": "through the wall"}])
            assert text
            await session.close()
            await provider.stop(drain_timeout_s=2)
            await server.stop()

        run(main())

    def test_relay_refused_for_unknown_provider(self):
        async def main():
            hub = MemoryTransport()
            ident = Identity.from_name("relay-server2")
            server = SymmetryServer(ident, hub, ping_interval_s=30.0)
            await server.start("mem://server")
            client = SymmetryClient(Identity.from_name("relay-cli2"), hub)
            with pytest.raises(ClientError, match="cannot relay"):
                await client.connect_relay(
                    "mem://server", ident.public_key, "ab" * 32)
            await server.stop()

        run(main())

    def test_relay_cannot_be_hijacked_by_third_party(self):
        """A third peer must not be able to impersonate the provider on a
        pending relay: connecting and sending relayAccept for someone
        else's relayId gets relayClose, and the end-to-end pinning means
        even a successful splice to the wrong node fails the handshake."""
        async def main():
            from symmetry_tpu.network.peer import Peer
            from symmetry_tpu.protocol.keys import MessageKey

            hub = MemoryTransport()
            ident = Identity.from_name("relay-server3")
            server = SymmetryServer(ident, hub, ping_interval_s=30.0)
            await server.start("mem://server")

            evil = Identity.from_name("relay-evil")
            conn = await hub.dial("mem://server")
            peer = await Peer.connect(conn, evil, initiator=True,
                                      expected_remote_key=ident.public_key)
            await peer.send(MessageKey.RELAY_ACCEPT, {"id": "not-a-relay"})
            msg = await asyncio.wait_for(peer.recv(), 5)
            assert msg is not None and msg.key == MessageKey.RELAY_CLOSE
            await peer.close()
            await server.stop()

        run(main())


class TestRequestHardening:
    """The `request` op must not be a reflection primitive: a source must
    echo a cookie (proving it receives at its claimed address) before any
    punch is brokered, and even proven sources have an invite budget
    (round-3 advisor finding)."""

    @staticmethod
    async def _udp_probe():
        loop = asyncio.get_running_loop()
        inbox: asyncio.Queue = asyncio.Queue()

        class _P(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                inbox.put_nowait(data)

        transport, _ = await loop.create_datagram_endpoint(
            _P, local_addr=("127.0.0.1", 0))
        return transport, inbox

    def test_uncookied_request_gets_challenge_only(self):
        async def main():
            import json as _json
            import time as _time

            from symmetry_tpu.network.natpunch import _msg, _register_sig_msg

            rdv = PunchRendezvous()
            await rdv.start("127.0.0.1", 0)
            prov = Identity.from_name("cookie-prov")
            ptrans, pinbox = await self._udp_probe()
            ts = _time.time()
            ptrans.sendto(
                wrap_raw(_msg("register", key=prov.public_hex,
                              ts=round(ts, 3),
                              sig=prov.sign(_register_sig_msg(
                                  prov.public_hex, ts)).hex())),
                ("127.0.0.1", rdv.port))
            assert _json.loads(unwrap_raw(
                await asyncio.wait_for(pinbox.get(), 5)))["op"] == "registered"

            ctrans, cinbox = await self._udp_probe()
            ctrans.sendto(wrap_raw(_msg("request", key=prov.public_hex)),
                          ("127.0.0.1", rdv.port))
            reply = _json.loads(unwrap_raw(
                await asyncio.wait_for(cinbox.get(), 5)))
            # no peer, no invite — only a challenge back to the source
            assert reply["op"] == "challenge" and reply["cookie"]
            assert pinbox.empty(), "provider must NOT be invited yet"

            # echoing the cookie completes the round-trip
            ctrans.sendto(
                wrap_raw(_msg("request", key=prov.public_hex,
                              cookie=reply["cookie"])),
                ("127.0.0.1", rdv.port))
            peer = _json.loads(unwrap_raw(
                await asyncio.wait_for(cinbox.get(), 5)))
            assert peer["op"] == "peer"
            invite = _json.loads(unwrap_raw(
                await asyncio.wait_for(pinbox.get(), 5)))
            assert invite["op"] == "invite"
            ptrans.close()
            ctrans.close()
            await rdv.stop()

        run(main())

    def test_invite_budget_per_source(self):
        from symmetry_tpu.network.natpunch import MAX_INVITES_PER_SOURCE

        rdv = PunchRendezvous()
        addr = ("198.51.100.7", 40000)
        for _ in range(MAX_INVITES_PER_SOURCE):
            assert rdv._invite_allowed(addr)
        assert not rdv._invite_allowed(addr)
        # other sources are unaffected
        assert rdv._invite_allowed(("198.51.100.8", 40000))

    def test_retransmissions_charge_budget_once(self):
        """punch_dial resends its request every second while replies are
        lost; those retransmissions must not burn the invite budget (one
        lossy dial would otherwise hard-fail the next legitimate one)."""
        import time as _time

        rdv = PunchRendezvous()
        sent = []
        rdv._send = lambda payload, addr: sent.append((payload, addr))
        prov_addr = ("203.0.113.5", 50000)
        rdv._registry["provkey"] = (prov_addr, _time.monotonic())
        addr = ("198.51.100.7", 40000)
        from symmetry_tpu.network.natpunch import _msg, wrap_raw

        cookie = rdv._cookie_for(addr)
        for _ in range(12):  # > MAX_INVITES_PER_SOURCE resends
            rdv._on_datagram(
                wrap_raw(_msg("request", key="provkey", cookie=cookie)),
                addr)
        import json as _json

        ops = [_json.loads(p.decode())["op"] for p, _ in sent]
        # every retransmission was ANSWERED (peer+invite), none rejected
        assert "busy" not in ops
        assert ops.count("peer") == 12 and ops.count("invite") == 12
        # and the budget was charged only once
        assert len(rdv._invites[addr]) == 1


class TestRelayCap:
    def test_relay_connect_capped_per_client(self):
        """One client key cannot hold more than MAX_RELAYS_PER_CLIENT
        pending/active splices (round-3 advisor: unbounded _relays growth
        + provider-side dial/task per RELAY_OPEN)."""
        async def main():
            from symmetry_tpu.protocol.keys import MessageKey

            class _FakePeer:
                closed = False

                def __init__(self):
                    self.sent = []

                async def send(self, key, data=None):
                    self.sent.append((key, data))

            hub = MemoryTransport()
            ident = Identity.from_name("cap-server")
            server = SymmetryServer(ident, hub, ping_interval_s=30.0)
            control = _FakePeer()
            server._provider_peers["prov-key"] = control
            client = _FakePeer()
            for _ in range(server.MAX_RELAYS_PER_CLIENT):
                await server._handle_relay_connect(
                    client, "client-key", {"providerKey": "prov-key"})
            assert len(server._relays) == server.MAX_RELAYS_PER_CLIENT
            assert all(k == MessageKey.RELAY_OPEN for k, _ in control.sent)
            await server._handle_relay_connect(
                client, "client-key", {"providerKey": "prov-key"})
            assert len(server._relays) == server.MAX_RELAYS_PER_CLIENT
            assert client.sent[-1][0] == MessageKey.RELAY_CLOSE
            # a different client key is unaffected
            await server._handle_relay_connect(
                _FakePeer(), "other-key", {"providerKey": "prov-key"})
            assert len(server._relays) == server.MAX_RELAYS_PER_CLIENT + 1

        run(main())


class TestServerHostedRendezvous:
    def test_server_starts_punch_rendezvous(self):
        """The routing server hosts the punch rendezvous (punch_port=0
        binds ephemeral); a signed register round-trips against it."""
        async def main():
            import time as _time

            from symmetry_tpu.network.natpunch import (
                _msg, _register_sig_msg, unwrap_raw)

            hub = MemoryTransport()
            ident = Identity.from_name("rdv-server")
            server = SymmetryServer(ident, hub, ping_interval_s=30.0,
                                    punch_port=0)
            await server.start("mem://server")
            assert server.punch_port

            prov = Identity.from_name("rdv-prov")
            loop = asyncio.get_running_loop()
            inbox: asyncio.Queue = asyncio.Queue()

            class _P(asyncio.DatagramProtocol):
                def datagram_received(self, data, addr):
                    inbox.put_nowait(data)

            transport, _ = await loop.create_datagram_endpoint(
                _P, local_addr=("127.0.0.1", 0))
            ts = _time.time()
            payload = _msg("register", key=prov.public_hex,
                           ts=round(ts, 3),
                           sig=prov.sign(_register_sig_msg(
                               prov.public_hex, ts)).hex())
            from symmetry_tpu.network.natpunch import wrap_raw

            transport.sendto(wrap_raw(payload),
                             ("127.0.0.1", server.punch_port))
            reply = unwrap_raw(await asyncio.wait_for(inbox.get(), 5))
            import json as _json

            assert _json.loads(reply)["op"] == "registered"
            transport.close()
            await server.stop()

        run(main())
