"""Tokenizer + incremental stream-decoding tests."""

from symmetry_tpu.engine.tokenizer import ByteTokenizer, StreamDecoder


class TestByteTokenizer:
    def test_roundtrip(self):
        tok = ByteTokenizer()
        text = "hello, wörld — ✓"
        assert tok.decode(tok.encode(text, bos=False)) == text

    def test_bos_eos(self):
        tok = ByteTokenizer()
        ids = tok.encode("x")
        assert ids[0] == tok.bos_id
        assert tok.EOS in tok.eos_ids
        assert tok.decode(ids + [tok.EOS]) == "x"  # specials skipped

    def test_chat_template_open_for_assistant(self):
        tok = ByteTokenizer()
        ids = tok.apply_chat_template(
            [{"role": "user", "content": "hi"}])
        assert tok.decode(ids).endswith("assistant: ")


class TestStreamDecoder:
    def test_ascii_streams_per_token(self):
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        got = [dec.push(i) for i in tok.encode("abc", bos=False)]
        assert got == ["a", "b", "c"]

    def test_multibyte_held_until_complete(self):
        """A split UTF-8 codepoint must never be emitted partially."""
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        ids = tok.encode("é✓", bos=False)  # 2-byte + 3-byte codepoints
        pieces = [dec.push(i) for i in ids]
        assert "".join(pieces) == "é✓"
        # No piece may contain a replacement char.
        assert all("�" not in p for p in pieces)
        # The bytes mid-codepoint must yield empty strings.
        assert pieces[0] == ""
        assert pieces[1] == "é"

    def test_flush_emits_dangling(self):
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        ids = tok.encode("é", bos=False)
        assert dec.push(ids[0]) == ""
        assert dec.push(ids[1]) == "é"
        assert dec.flush() == ""

    def test_long_stream_linear_cost(self):
        """The decode window must not grow with the stream (O(n^2) guard)."""
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        for i in tok.encode("x" * 5000, bos=False):
            dec.push(i)
        # Window is [prefix:], which must have stayed bounded.
        assert len(dec._ids) - dec._prefix <= 4


class TestPushManyBlockBoundaries:
    """push_many under the BLOCK emit path: multi-byte UTF-8 sequences
    split across decode-block boundaries — and across the ragged run
    sizes a speculative verify dispatch produces (1..1+k tokens per
    dispatch, a rollback shrinking a run to a single token) — must
    stream byte-identically to one-token-at-a-time decoding, holding
    partial codepoints back and never emitting a replacement char
    mid-stream."""

    # 1-, 2-, 3-, and 4-byte codepoints interleaved with ASCII.
    TEXT = "aé✓🌍xé🌍b✓✓é🌍🌍c"

    @staticmethod
    def _chunks(ids, sizes):
        """Split ids into runs of the given sizes, cycling."""
        out, i, s = [], 0, 0
        while i < len(ids):
            n = sizes[s % len(sizes)]
            out.append(ids[i:i + n])
            i += n
            s += 1
        return out

    def _assert_stream_equal(self, sizes):
        tok = ByteTokenizer()
        ids = tok.encode(self.TEXT, bos=False)
        ref_dec = StreamDecoder(tok)
        ref_pieces = [ref_dec.push(i) for i in ids]
        ref = "".join(ref_pieces) + ref_dec.flush()

        dec = StreamDecoder(tok)
        pieces = [dec.push_many(run) for run in self._chunks(ids, sizes)]
        got = "".join(pieces) + dec.flush()
        assert got == ref == self.TEXT
        # Mid-stream pieces never carry a replacement char: incomplete
        # codepoints are held back, not mangled.
        assert all("�" not in p for p in pieces)

    def test_fixed_block_boundaries(self):
        """Plain decode blocks: every fixed run size must split at least
        one multi-byte codepoint across a boundary."""
        for size in (1, 2, 3, 4, 5, 7):
            self._assert_stream_equal([size])

    def test_speculative_ragged_runs(self):
        """Verify-dispatch shapes: accepted-run lengths vary dispatch to
        dispatch (full acceptance, partial, total rollback to 1)."""
        self._assert_stream_equal([5, 1, 3, 1, 1, 4, 2])

    def test_rollback_to_single_token_mid_codepoint(self):
        """A speculative rollback landing mid-codepoint: the 4-byte 🌍
        arrives as 2 + 1 + 1 tokens across three dispatches and must
        emit exactly once, complete, on the final one."""
        tok = ByteTokenizer()
        ids = tok.encode("🌍", bos=False)
        assert len(ids) == 4
        dec = StreamDecoder(tok)
        assert dec.push_many(ids[:2]) == ""
        assert dec.push_many([ids[2]]) == ""
        assert dec.push_many([ids[3]]) == "🌍"
        assert dec.flush() == ""

    def test_empty_run_is_noop(self):
        """A slot whose whole run was discarded pushes nothing."""
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        assert dec.push_many([]) == ""
        assert dec.push_many(tok.encode("é", bos=False)) == "é"
