"""Tokenizer + incremental stream-decoding tests."""

from symmetry_tpu.engine.tokenizer import ByteTokenizer, StreamDecoder


class TestByteTokenizer:
    def test_roundtrip(self):
        tok = ByteTokenizer()
        text = "hello, wörld — ✓"
        assert tok.decode(tok.encode(text, bos=False)) == text

    def test_bos_eos(self):
        tok = ByteTokenizer()
        ids = tok.encode("x")
        assert ids[0] == tok.bos_id
        assert tok.EOS in tok.eos_ids
        assert tok.decode(ids + [tok.EOS]) == "x"  # specials skipped

    def test_chat_template_open_for_assistant(self):
        tok = ByteTokenizer()
        ids = tok.apply_chat_template(
            [{"role": "user", "content": "hi"}])
        assert tok.decode(ids).endswith("assistant: ")


class TestStreamDecoder:
    def test_ascii_streams_per_token(self):
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        got = [dec.push(i) for i in tok.encode("abc", bos=False)]
        assert got == ["a", "b", "c"]

    def test_multibyte_held_until_complete(self):
        """A split UTF-8 codepoint must never be emitted partially."""
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        ids = tok.encode("é✓", bos=False)  # 2-byte + 3-byte codepoints
        pieces = [dec.push(i) for i in ids]
        assert "".join(pieces) == "é✓"
        # No piece may contain a replacement char.
        assert all("�" not in p for p in pieces)
        # The bytes mid-codepoint must yield empty strings.
        assert pieces[0] == ""
        assert pieces[1] == "é"

    def test_flush_emits_dangling(self):
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        ids = tok.encode("é", bos=False)
        assert dec.push(ids[0]) == ""
        assert dec.push(ids[1]) == "é"
        assert dec.flush() == ""

    def test_long_stream_linear_cost(self):
        """The decode window must not grow with the stream (O(n^2) guard)."""
        tok = ByteTokenizer()
        dec = StreamDecoder(tok)
        for i in tok.encode("x" * 5000, bos=False):
            dec.push(i)
        # Window is [prefix:], which must have stayed bounded.
        assert len(dec._ids) - dec._prefix <= 4
