"""Config manager: reference-parity validation + TPU extensions."""

import pytest
import yaml

from symmetry_tpu.provider.config import ConfigError, ConfigManager, write_default_config

BASE = {
    "name": "node-1",
    "public": True,
    "serverKey": "ab" * 32,
    "modelName": "llama3:8b",
    "apiProvider": "ollama",
    "apiHostname": "localhost",
    "apiPort": 11434,
    "apiPath": "/v1/chat/completions",
    "apiProtocol": "http",
}


def test_valid_proxy_config():
    cfg = ConfigManager(config=BASE)
    assert cfg.model_name == "llama3:8b"
    assert cfg.max_connections == 10  # default, reference install.sh:44
    assert cfg.server_key == bytes.fromhex("ab" * 32)


def test_missing_required_fields_rejected():
    # Required-field validation parity (reference src/config.ts:19-45).
    for drop in ("name", "modelName", "serverKey", "public", "apiHostname"):
        broken = {k: v for k, v in BASE.items() if k != drop}
        with pytest.raises(ConfigError, match=drop):
            ConfigManager(config=broken)


def test_public_must_be_boolean():
    # Reference enforces boolean `public` (src/config.ts:40-44).
    with pytest.raises(ConfigError, match="boolean"):
        ConfigManager(config={**BASE, "public": "yes"})


def test_tpu_native_needs_no_api_fields():
    cfg = ConfigManager(config={
        "name": "tpu-node", "public": False, "serverKey": "cd" * 32,
        "modelName": "llama3:8b", "apiProvider": "tpu_native",
        "tpu": {"mesh": {"data": 1, "model": 8}, "dtype": "bfloat16",
                "max_batch_size": 16},
    })
    assert cfg.tpu.mesh == {"data": 1, "model": 8}
    assert cfg.tpu.max_batch_size == 16
    assert cfg.tpu.model_family == "llama"


def test_speculative_knob_accepted():
    cfg = ConfigManager(config={
        "name": "tpu-node", "public": False, "serverKey": "cd" * 32,
        "modelName": "llama3:8b", "apiProvider": "tpu_native",
        "tpu": {"speculative": {"k_draft": 4}},
    })
    assert cfg.tpu.speculative == {"k_draft": 4}
    # off by default — the engine builds no verify path then
    assert ConfigManager(config={
        "name": "t", "public": False, "serverKey": "cd" * 32,
        "modelName": "m", "apiProvider": "tpu_native",
    }).tpu.speculative is None


def test_unknown_provider_rejected():
    with pytest.raises(ConfigError, match="apiProvider"):
        ConfigManager(config={**BASE, "apiProvider": "vllm"})


def test_unknown_tpu_keys_rejected():
    with pytest.raises(ConfigError, match="unknown tpu"):
        ConfigManager(config={**BASE, "apiProvider": "tpu_native",
                              "tpu": {"mesh_shap": {}}})


def test_api_key_stripped_from_public_view():
    # The reference announces its full config incl. apiKey to the server
    # (src/provider.ts:103-108) — we must not.
    cfg = ConfigManager(config={**BASE, "apiKey": "sk-secret"})
    assert "apiKey" not in cfg.public_view()
    assert cfg.get("apiKey") == "sk-secret"


def test_yaml_load_and_scaffold(tmp_path):
    path = tmp_path / "provider.yaml"
    write_default_config(str(path), name="scaffolded", server_key_hex="ef" * 32)
    cfg = ConfigManager(config_path=str(path))
    assert cfg.name == "scaffolded"
    assert cfg.api_provider == "tpu_native"
    # Round-trips through real YAML on disk.
    raw = yaml.safe_load(path.read_text())
    assert raw["serverKey"] == "ef" * 32
