"""Per-connection write corking (transport/base.WriteCork + TCP).

The provider fan-out of one batched engine block wakes many per-request
pumps in the same event-loop tick, each sending a frame to (possibly)
the same peer. The cork must collapse those same-tick sends into ONE
transport write+drain while preserving send order and the per-send
backpressure contract (send returns only after its bytes drained).
"""

import asyncio

from symmetry_tpu.transport.base import WriteCork
from symmetry_tpu.transport.tcp import TcpTransport


def run(coro):
    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(coro, 30))


class TestWriteCork:
    def test_same_tick_sends_coalesce_into_one_write(self):
        sent: list[bytes] = []

        async def write_drain(data: bytes) -> None:
            sent.append(data)

        async def main():
            cork = WriteCork(write_drain)
            await asyncio.gather(cork.send(b"aa"), cork.send(b"bb"),
                                 cork.send(b"cc"))
            return cork.stats

        stats = run(main())
        assert sent == [b"aabbcc"]  # one write, send order preserved
        assert stats == {"writes": 1, "frames": 3, "coalesced_frames": 2,
                         "bytes": 6}

    def test_cross_tick_sends_write_separately(self):
        sent: list[bytes] = []

        async def write_drain(data: bytes) -> None:
            sent.append(data)

        async def main():
            cork = WriteCork(write_drain)
            for i in range(3):
                await cork.send(b"%d" % i)  # sequential: a tick each
            return cork.stats

        stats = run(main())
        assert b"".join(sent) == b"012"
        assert stats["frames"] == 3
        assert stats["writes"] == len(sent)

    def test_backpressure_holds_senders_until_drain(self):
        release = asyncio.Event()
        drained = []

        async def write_drain(data: bytes) -> None:
            await release.wait()
            drained.append(data)

        async def main():
            cork = WriteCork(write_drain)
            senders = [asyncio.ensure_future(cork.send(b"x"))
                       for _ in range(4)]
            await asyncio.sleep(0.05)
            assert not any(t.done() for t in senders)  # all backpressured
            release.set()
            await asyncio.gather(*senders)
            assert drained == [b"xxxx"]

        run(main())

    def test_sends_during_inflight_drain_keep_order_one_flusher(self):
        """Frames arriving while a drain is in flight batch onto the NEXT
        write of the SAME flusher task — ordering must hold even for a
        write_drain that suspends before touching the wire (TLS wrap, a
        relay splice), so it cannot rest on writer.write() being sync."""
        sent: list[bytes] = []

        async def write_drain(data: bytes) -> None:
            await asyncio.sleep(0.02)  # suspend BEFORE the bytes land
            sent.append(data)

        async def main():
            cork = WriteCork(write_drain)
            a = asyncio.ensure_future(cork.send(b"A"))
            await asyncio.sleep(0.01)  # A's drain now in flight
            b = asyncio.ensure_future(cork.send(b"B"))
            c = asyncio.ensure_future(cork.send(b"C"))
            await asyncio.gather(a, b, c)
            return cork.stats

        stats = run(main())
        assert sent == [b"A", b"BC"]  # second batch after, not interleaved
        assert stats == {"writes": 2, "frames": 3, "coalesced_frames": 1,
                         "bytes": 3}

    def test_cancelled_sender_does_not_poison_coalesced_peers(self):
        """The flush future is shared by every sender in a batch; one
        cancelled sender (its stream's pump torn down mid-flight) must
        not cancel the write out from under the others."""
        sent: list[bytes] = []

        async def write_drain(data: bytes) -> None:
            await asyncio.sleep(0.02)
            sent.append(data)

        async def main():
            cork = WriteCork(write_drain)
            a = asyncio.ensure_future(cork.send(b"A"))
            b = asyncio.ensure_future(cork.send(b"B"))
            await asyncio.sleep(0.01)  # both coalesced, drain in flight
            a.cancel()
            await b  # must complete cleanly, not raise CancelledError
            assert a.cancelled()
            assert sent == [b"AB"]  # the batch still hit the wire intact

        run(main())

    def test_write_failure_fails_every_awaiting_sender(self):
        async def write_drain(data: bytes) -> None:
            raise ConnectionResetError("peer gone")

        async def main():
            cork = WriteCork(write_drain)
            results = await asyncio.gather(
                cork.send(b"a"), cork.send(b"b"), return_exceptions=True)
            assert all(isinstance(r, ConnectionResetError)
                       for r in results)

        run(main())


class TestTcpCork:
    def test_burst_collapses_frames_and_preserves_order(self):
        async def main():
            received: list[bytes] = []
            done = asyncio.Event()

            async def handler(conn):
                while True:
                    frame = await conn.recv()
                    if frame is None:
                        return
                    received.append(frame)
                    if len(received) == 20:
                        done.set()

            transport = TcpTransport()
            listener = await transport.listen("tcp://127.0.0.1:0", handler)
            conn = await transport.dial(listener.address)
            frames = [b"frame-%02d" % i for i in range(20)]
            await asyncio.gather(*(conn.send(f) for f in frames))
            await asyncio.wait_for(done.wait(), 10)

            assert received == frames  # boundaries + order intact
            stats = conn.write_stats
            assert stats["frames"] == 20
            # the same-tick burst coalesces into (nearly) one write
            assert stats["writes"] <= 2
            assert stats["coalesced_frames"] >= 18
            await conn.close()
            await listener.close()
            await asyncio.sleep(0.02)  # let server-side handlers finish

        run(main())

    def test_close_settles_pending_corked_frames(self):
        """close() racing the flusher in the same tick must settle the
        cork first — a frame send() accepted (e.g. a stream's final
        done frame during a disconnect) must reach the wire, not be
        buffered-and-discarded by the writer teardown."""
        async def main():
            received: list[bytes] = []
            got2 = asyncio.Event()

            async def handler(conn):
                while True:
                    frame = await conn.recv()
                    if frame is None:
                        return
                    received.append(frame)
                    if len(received) == 2:
                        got2.set()

            transport = TcpTransport()
            listener = await transport.listen("tcp://127.0.0.1:0", handler)
            conn = await transport.dial(listener.address)
            s1 = asyncio.ensure_future(conn.send(b"final-1"))
            s2 = asyncio.ensure_future(conn.send(b"final-2"))
            await asyncio.sleep(0)  # senders buffered into the cork
            await conn.close()      # races the flusher
            await asyncio.gather(s1, s2)
            await asyncio.wait_for(got2.wait(), 10)
            assert received == [b"final-1", b"final-2"]
            await listener.close()
            await asyncio.sleep(0.02)  # let server-side handlers finish

        run(main())

    def test_sequential_sends_still_work(self):
        async def main():
            received: list[bytes] = []
            got3 = asyncio.Event()

            async def handler(conn):
                while True:
                    frame = await conn.recv()
                    if frame is None:
                        return
                    received.append(frame)
                    if len(received) == 3:
                        got3.set()

            transport = TcpTransport()
            listener = await transport.listen("tcp://127.0.0.1:0", handler)
            conn = await transport.dial(listener.address)
            for payload in (b"one", b"two", b"three"):
                await conn.send(payload)
            await asyncio.wait_for(got3.wait(), 10)
            assert received == [b"one", b"two", b"three"]
            await conn.close()
            await listener.close()
            await asyncio.sleep(0.02)  # let server-side handlers finish

        run(main())
