"""Protocol-faithful engine-host stand-in for the supervisor chaos suite.

Speaks exactly the engine/host.py JSON-lines pipe protocol (ready, clock
handshake, stats, submit → event stream, cancel, shutdown) without
importing JAX or building a model, so a supervisor test can kill, wedge,
and respawn host "lives" in milliseconds instead of paying an engine
build per life. The chaos seams are the REAL ones — every pipe write
passes `FAULTS.point("host.pipe_write")` and every command read passes
`FAULTS.point("host.pipe_read")` (symmetry_tpu/utils/faults.py), armed
through the same `faults:` config mapping / SYMMETRY_FAULTS env the real
host honors.

Extra config under `fakeHost:` (test-only):
  failFile:   if this path exists at startup, exit(1) BEFORE ready —
              simulates a persistently failing respawn (circuit-breaker
              fixture; each life re-checks, so the test controls when
              respawns start failing by creating/removing the file)
  tokenDelayS: inter-event sleep while streaming (default 0.02 s), wide
              enough that an armed crash reliably lands mid-stream
  dieAfterS:  hard-crash (os._exit) this long after ready — the
              crash-LOOP fixture: every spawn succeeds, every life dies
              young, and the supervisor's stability accounting (not
              spawn success) must walk it into the circuit breaker

Run: python tests/fake_host.py <config.yaml>
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import yaml

# Script-path execution puts tests/ (not the repo root) on sys.path; the
# real host avoids this via `-m`. Make symmetry_tpu importable regardless
# of the spawning process's cwd.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from symmetry_tpu.protocol.keys import HostOp
from symmetry_tpu.utils.faults import FAULTS  # noqa: E402


class FakeHost:
    def __init__(self, cfg: dict) -> None:
        self._cfg = cfg
        self._wlock = threading.Lock()
        self._cancelled: set[str] = set()
        fh = cfg.get("fakeHost") or {}
        self._fail_path = fh.get("failFile")
        self._delay = float(fh.get("tokenDelayS", 0.02))
        self._die_after = fh.get("dieAfterS")
        FAULTS.load(cfg.get("faults"))

    def write(self, obj: dict) -> None:
        if FAULTS.enabled and FAULTS.point("host.pipe_write"):
            return  # injected drop_frame
        with self._wlock:
            sys.stdout.write(json.dumps(obj, separators=(",", ":")) + "\n")
            sys.stdout.flush()

    def _stream(self, msg: dict) -> None:
        req_id = str(msg.get("id", ""))
        n = max(1, min(int(msg.get("max_new", 4)), 64))
        for i in range(n - 1):
            if req_id in self._cancelled:
                break
            self.write({"op": HostOp.EVENT, "id": req_id, "text": f"t{i} ",
                        "tokens": i + 1, "tokens_new": 1})
            time.sleep(self._delay)
        self.write({"op": HostOp.EVENT, "id": req_id, "text": "", "done": True,
                    "finish_reason": "stop", "tokens": n, "tokens_new": 0})
        self._cancelled.discard(req_id)

    def serve(self) -> int:
        if self._fail_path and os.path.exists(self._fail_path):
            print("fake host: failFile present; dying before ready",
                  file=sys.stderr)
            return 1
        if self._die_after is not None:
            threading.Timer(float(self._die_after),
                            lambda: os._exit(86)).start()
        self.write({"op": HostOp.READY, "model": self._cfg.get("modelName", "fake"),
                    "slots": 4, "max_seq_len": 128,
                    "build_s": 0.0, "warmup_s": 0.0})
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            if FAULTS.enabled and FAULTS.point("host.pipe_read"):
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            op = msg.get("op")
            if op == HostOp.CLOCK:
                self.write({"op": HostOp.CLOCK, "t0": msg.get("t0"),
                            "t": time.monotonic()})
            elif op == HostOp.STATS:
                self.write({"op": HostOp.STATS, "engine_alive": True,
                            "requests": 0, "tokens": 0,
                            **({"faults": FAULTS.counters()}
                               if FAULTS.enabled else {})})
            elif op == HostOp.SUBMIT:
                threading.Thread(target=self._stream, args=(msg,),
                                 daemon=True).start()
            elif op == HostOp.CANCEL:
                self._cancelled.add(str(msg.get("id", "")))
            elif op == HostOp.TRACE:
                self.write({"op": HostOp.TRACE, "clock": time.monotonic(),
                            "components": []})
            elif op == HostOp.METRICS:
                # Real registry snapshot (tiny here — no families were
                # emitted) so the backend's tier-labeling merge path is
                # exercised against the true wire shape.
                from symmetry_tpu.utils.metrics import METRICS

                self.write({"op": HostOp.METRICS, "role": "unified",
                            **METRICS.snapshot(compact=True)})
            elif op == HostOp.SHUTDOWN:
                return 0
        return 0


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: python tests/fake_host.py <config.yaml>",
              file=sys.stderr)
        return 2
    with open(sys.argv[1], "r", encoding="utf-8") as fh:
        cfg = yaml.safe_load(fh) or {}
    return FakeHost(cfg).serve()


if __name__ == "__main__":
    sys.exit(main())
