"""Protocol-faithful engine-host stand-in for the supervisor chaos suite.

Speaks exactly the engine/host.py JSON-lines pipe protocol (ready, clock
handshake, stats, submit → event stream, cancel, shutdown) without
importing JAX or building a model, so a supervisor test can kill, wedge,
and respawn host "lives" in milliseconds instead of paying an engine
build per life. The chaos seams are the REAL ones — every pipe write
passes `FAULTS.point("host.pipe_write")` and every command read passes
`FAULTS.point("host.pipe_read")` (symmetry_tpu/utils/faults.py), armed
through the same `faults:` config mapping / SYMMETRY_FAULTS env the real
host honors.

Extra config under `fakeHost:` (test-only):
  failFile:   if this path exists at startup, exit(1) BEFORE ready —
              simulates a persistently failing respawn (circuit-breaker
              fixture; each life re-checks, so the test controls when
              respawns start failing by creating/removing the file)
  tokenDelayS: inter-event sleep while streaming (default 0.02 s), wide
              enough that an armed crash reliably lands mid-stream
  dieAfterS:  hard-crash (os._exit) this long after ready — the
              crash-LOOP fixture: every spawn succeeds, every life dies
              young, and the supervisor's stability accounting (not
              spawn success) must walk it into the circuit breaker

Run: python tests/fake_host.py <config.yaml>
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import yaml

# Script-path execution puts tests/ (not the repo root) on sys.path; the
# real host avoids this via `-m`. Make symmetry_tpu importable regardless
# of the spawning process's cwd.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from symmetry_tpu.protocol.keys import HostOp
from symmetry_tpu.utils.faults import FAULTS  # noqa: E402


class FakeHost:
    def __init__(self, cfg: dict) -> None:
        self._cfg = cfg
        self._wlock = threading.Lock()
        self._cancelled: set[str] = set()
        # Emitted-token journal (mirrors EngineHost._reported): rides
        # the stats reply so the supervisor's sheds stamp counts.
        self._reported: dict[str, int] = {}
        fh = cfg.get("fakeHost") or {}
        self._fail_path = fh.get("failFile")
        self._delay = float(fh.get("tokenDelayS", 0.02))
        self._die_after = fh.get("dieAfterS")
        # Tier role (tpu.role, pinned by derive_role_config): a
        # "prefill" fake emits routing-only handoff frames instead of
        # token events; a "decode" fake adopts and streams — the
        # protocol shapes the pool/disagg chaos drills exercise.
        self._role = str((cfg.get("tpu") or {}).get("role") or "unified")
        FAULTS.load(cfg.get("faults"))

    def write(self, obj: dict) -> None:
        if FAULTS.enabled and FAULTS.point("host.pipe_write"):
            return  # injected drop_frame
        with self._wlock:
            sys.stdout.write(json.dumps(obj, separators=(",", ":")) + "\n")
            sys.stdout.flush()

    def _handoff(self, msg: dict) -> None:
        """Prefill role: one submit → one routing-only handoff frame
        (p=0 — the decode tier full-prefills; real KV extraction needs
        the real engine). Same wire shape engine/host.py emits."""
        import base64

        from symmetry_tpu.engine.disagg.frames import encode_kv_handoff

        req_id = str(msg.get("id", ""))
        if FAULTS.enabled and FAULTS.point("disagg.handoff"):
            return  # injected drop/crash at the handoff seam
        tokens = list(range(8))
        frame = encode_kv_handoff(req_id, tokens, 0, None)
        time.sleep(self._delay)  # prefill "work" — churn lands mid-flight
        self.write({"op": HostOp.HANDOFF, "id": req_id, "p": 0,
                    "prompt_len": len(tokens), "nbytes": len(frame),
                    "t": time.monotonic(),
                    "frame": base64.b64encode(frame).decode("ascii")})

    def _stream(self, msg: dict) -> None:
        req_id = str(msg.get("id", ""))
        n = max(1, min(int(msg.get("max_new", 4)), 64))
        with self._wlock:  # stats copies this dict under the same lock
            self._reported[req_id] = 0
        # Stream resumption, protocol-faithful: the deterministic
        # completion for max_new=n is "t0 t1 … t{n-2} ", so a resume
        # with R received tokens continues at t{R} — exactly the real
        # host's continue-from-the-client's-text semantics, with the
        # first event carrying the `reused`/`resume_from` riders (the
        # fake's "radix hit" is the whole prompt+emitted run).
        # `fakeHost.resumeOverlap: K` deliberately REWINDS the
        # continuation K tokens (resume_from = R − K) — the overlap
        # fixture the backend's offset dedup is tested against.
        start = 0
        resumed = None
        resume = msg.get("resume")
        if isinstance(resume, dict):
            claimed = resume.get("tokens")
            if claimed is not None:
                start = max(0, int(claimed))
            else:
                # One token per "t{i} " word, same as emission.
                start = len(str(resume.get("text") or "").split())
            overlap = int((self._cfg.get("fakeHost") or {})
                          .get("resumeOverlap", 0))
            resumed = max(0, start - overlap)
            start = resumed
        first = True
        for i in range(start, n - 1):
            if req_id in self._cancelled:
                break
            ev = {"op": HostOp.EVENT, "id": req_id, "text": f"t{i} ",
                  "tokens": i + 1, "tokens_new": 1}
            if first and resumed is not None:
                ev["resume_from"] = resumed
                ev["reused"] = max(resumed, 1)
            first = False
            self.write(ev)
            with self._wlock:
                self._reported[req_id] += 1
            time.sleep(self._delay)
        self.write({"op": HostOp.EVENT, "id": req_id, "text": "", "done": True,
                    "finish_reason": "stop", "tokens": n, "tokens_new": 0})
        with self._wlock:
            self._reported.pop(req_id, None)
        self._cancelled.discard(req_id)

    def serve(self) -> int:
        if self._fail_path and os.path.exists(self._fail_path):
            print("fake host: failFile present; dying before ready",
                  file=sys.stderr)
            return 1
        if self._die_after is not None:
            threading.Timer(float(self._die_after),
                            lambda: os._exit(86)).start()
        self.write({"op": HostOp.READY, "model": self._cfg.get("modelName", "fake"),
                    "slots": 4, "max_seq_len": 128,
                    "build_s": 0.0, "warmup_s": 0.0})
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            if FAULTS.enabled and FAULTS.point("host.pipe_read"):
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            op = msg.get("op")
            if op == HostOp.CLOCK:
                self.write({"op": HostOp.CLOCK, "t0": msg.get("t0"),
                            "t": time.monotonic()})
            elif op == HostOp.STATS:
                with self._wlock:
                    journal = dict(self._reported)
                self.write({"op": HostOp.STATS, "engine_alive": True,
                            "requests": 0, "tokens": 0,
                            "queue_depth": 0, "role": self._role,
                            "journal": journal,
                            **({"faults": FAULTS.counters()}
                               if FAULTS.enabled else {})})
            elif op == HostOp.SUBMIT:
                target = (self._handoff if self._role == "prefill"
                          else self._stream)
                threading.Thread(target=target, args=(msg,),
                                 daemon=True).start()
            elif op == HostOp.ADOPT:
                # Decode role: a migrated request streams exactly like a
                # submit (the real host parses the frame on the engine
                # thread; the fake has no engine to seed).
                threading.Thread(target=self._stream, args=(msg,),
                                 daemon=True).start()
            elif op == HostOp.CANCEL:
                self._cancelled.add(str(msg.get("id", "")))
            elif op == HostOp.TRACE:
                self.write({"op": HostOp.TRACE, "clock": time.monotonic(),
                            "components": []})
            elif op == HostOp.METRICS:
                # Real registry snapshot (tiny here — no families were
                # emitted) so the backend's tier-labeling merge path is
                # exercised against the true wire shape.
                from symmetry_tpu.utils.metrics import METRICS

                self.write({"op": HostOp.METRICS, "role": "unified",
                            **METRICS.snapshot(compact=True)})
            elif op == HostOp.SHUTDOWN:
                return 0
        return 0


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: python tests/fake_host.py <config.yaml>",
              file=sys.stderr)
        return 2
    with open(sys.argv[1], "r", encoding="utf-8") as fh:
        cfg = yaml.safe_load(fh) or {}
    return FakeHost(cfg).serve()


if __name__ == "__main__":
    sys.exit(main())
