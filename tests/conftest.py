"""Test configuration.

Tests never require a real TPU: JAX is pinned to the CPU backend with 8 virtual
devices so sharding/mesh tests exercise real multi-device compilation paths
(SURVEY §4 build implication). This must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
