"""Test configuration.

Tests never require a real TPU: JAX is pinned to the CPU backend with 8 virtual
devices so sharding/mesh tests exercise real multi-device compilation paths
(SURVEY §4 build implication). This must run before jax is imported anywhere —
and must OVERRIDE the outer environment, which may point JAX_PLATFORMS at a
live TPU tunnel.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env pinning, by design)

# A site hook may have re-pointed jax_platforms at a live TPU despite the env
# var (observed: sitecustomize forcing "axon,cpu"); pin it back post-import.
jax.config.update("jax_platforms", "cpu")

# Tests run models in float32 and compare against f32 references; the default
# matmul precision truncates f32 operands to bf16 passes, which swamps the
# tolerances. Production serving uses bf16 params, where this is a no-op.
jax.config.update("jax_default_matmul_precision", "highest")

# Persistent compilation cache: every test that builds an engine re-traces
# the same programs; caching compiled executables across tests AND runs is
# the difference between an affordable suite and a >10-minute one. The env
# vars propagate it to SUBPROCESSES (graft dryrun, engine hosts, multihost
# workers); jax.config covers this already-imported process.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/symmetry-tpu-jax-test-cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
