"""Rank-0 process for the multi-host provider E2E test: runs the server,
the rank-0 provider (tpu_native, 2-process mesh), and a client chat — the
full BASELINE config-5 shape at tiny scale."""

import asyncio
import json
import os
import sys


def main() -> None:
    port = sys.argv[1]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")

    from symmetry_tpu.client.client import SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.memory import MemoryTransport

    async def run() -> None:
        hub = MemoryTransport()
        server_ident = Identity.from_name("mh-server")
        server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
        await server.start("mem://server")

        cfg = ConfigManager(config={
            "name": "mh-prov", "public": True,
            "serverKey": server_ident.public_hex,
            "modelName": "tiny:mh", "apiProvider": "tpu_native",
            "tpu": {
                "model_preset": "tiny", "dtype": "float32",
                "max_batch_size": 2, "max_seq_len": 64,
                "prefill_buckets": [32], "decode_block": 2,
                "mesh": {"model": 2},
                "multihost": {"coordinator": f"127.0.0.1:{port}",
                              "num_processes": 2, "process_id": 0,
                              "dcn_data": 2},
            },
        })
        provider = SymmetryProvider(cfg, transport=hub,
                                    identity=Identity.from_name("mh-prov"),
                                    server_address="mem://server")
        await provider.start("mem://mh-prov")
        await provider.wait_registered()

        client = SymmetryClient(Identity.from_name("mh-cli"), hub)
        details = await client.request_provider(
            "mem://server", server_ident.public_key, "tiny:mh")
        session = await client.connect(details)
        deltas = []
        async for d in session.chat([{"role": "user", "content": "hi"}],
                                    max_tokens=6):
            deltas.append(d)
        await session.close()
        await provider.stop()   # also releases the worker rank
        await server.stop()
        print("RESULT " + json.dumps({"text_len": len("".join(deltas)),
                                      "ok": True}), flush=True)

    asyncio.run(asyncio.wait_for(run(), 240))


if __name__ == "__main__":
    main()
