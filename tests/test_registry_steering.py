"""Registry steering hygiene: reported backlog freshness and typing.

(Split from test_server.py so the sqlite data model is testable without
the broker's crypto stack.)
"""

import time

from symmetry_tpu.server.registry import Registry


def add(reg: Registry, key: str) -> None:
    reg.upsert_provider(peer_key=key, discovery_key="d-" + key,
                        model_name="m", max_connections=10)


def queued_of(reg: Registry, key: str) -> int:
    row = reg._db.execute(
        "SELECT queued FROM peers WHERE peer_key = ?", (key,)).fetchone()
    return row["queued"]


def test_bool_queued_is_not_a_backlog():
    """isinstance(True, int) holds — a provider reporting queued=True
    must not be steered away from as if it had backlog 1."""
    reg = Registry()
    add(reg, "a")
    reg.set_metrics("a", {"queued": True})
    assert queued_of(reg, "a") == 0
    reg.set_metrics("a", {"queued": 3})
    assert queued_of(reg, "a") == 3
    reg.set_metrics("a", {"queued": False})
    assert queued_of(reg, "a") == 0


def test_fresh_backlog_steers_away():
    reg = Registry()
    add(reg, "busy")
    add(reg, "idle")
    reg.set_metrics("busy", {"queued": 64})
    reg.set_metrics("idle", {"queued": 0})
    # make `busy` otherwise preferable, so only the backlog steers
    reg.set_connections("idle", 5)
    assert reg.select_provider("m").peer_key == "idle"


def test_stale_backlog_decays_to_zero():
    """Shed-triggered METRICS pushes stop once the backlog drains; after
    ~2 report intervals without a fresh report the old reading must stop
    deprioritizing the (now idle) provider."""
    reg = Registry()
    add(reg, "busy")
    add(reg, "idle")
    reg.set_metrics("busy", {"queued": 64})
    reg.set_metrics("idle", {"queued": 0})
    reg.set_connections("idle", 5)  # `busy` wins on load once decayed
    # age the backlog report past the staleness horizon; liveness pings
    # (touch) keep last_seen fresh — only queued_at governs decay
    reg._db.execute("UPDATE peers SET queued_at = ? WHERE peer_key = ?",
                    (time.time() - Registry.QUEUED_STALE_S - 1, "busy"))
    reg._db.commit()
    reg.touch("busy")
    assert reg.select_provider("m").peer_key == "busy"


def test_fresh_report_resets_staleness():
    reg = Registry()
    add(reg, "busy")
    add(reg, "idle")
    reg.set_metrics("busy", {"queued": 64})
    reg._db.execute("UPDATE peers SET queued_at = ? WHERE peer_key = ?",
                    (time.time() - Registry.QUEUED_STALE_S - 1, "busy"))
    reg._db.commit()
    reg.set_metrics("busy", {"queued": 32})  # fresh shed report
    reg.set_metrics("idle", {"queued": 0})
    reg.set_connections("idle", 5)
    assert reg.select_provider("m").peer_key == "idle"
