"""Fused KV-append kernel (ops/kv_append.py) vs the XLA scatter path.

The kernel must be a drop-in for quantize_kv + the four cache scatters:
same scale math, same rows written, neighbours untouched, out-of-range
positions harmless. Runs in interpret mode (CPU); the TPU path is the
same kernel body."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.ops import kv_append as kva
from symmetry_tpu.ops.quant import quantize_kv

L, B, T, K, D = 3, 8, 64, 2, 128


def reference_append(cache_k, cache_v, k_scale, v_scale, k_new, v_new,
                     layer, positions):
    """The XLA path from models/llama.py _layer, S=1."""
    b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
    l_idx = jnp.full((B, 1), layer, jnp.int32)
    pos = positions[:, None]
    kq, ks = quantize_kv(k_new[:, None])   # [B, 1, K, D] -> scale [B, 1, K]
    vq, vs = quantize_kv(v_new[:, None])
    return (cache_k.at[l_idx, b_idx, pos].set(kq),
            cache_v.at[l_idx, b_idx, pos].set(vq),
            k_scale.at[l_idx, b_idx, :, pos].set(ks),
            v_scale.at[l_idx, b_idx, :, pos].set(vs))


def make_state(seed=0):
    key = jax.random.key(seed)
    ks = jax.random.split(key, 6)
    cache_k = jax.random.randint(ks[0], (L, B, T, K, D), -127, 127, jnp.int8)
    cache_v = jax.random.randint(ks[1], (L, B, T, K, D), -127, 127, jnp.int8)
    k_scale = jax.random.uniform(ks[2], (L, B, K, T), jnp.float32)
    v_scale = jax.random.uniform(ks[3], (L, B, K, T), jnp.float32)
    k_new = jax.random.normal(ks[4], (B, K, D), jnp.float32) * 3.0
    v_new = jax.random.normal(ks[5], (B, K, D), jnp.float32) * 3.0
    return cache_k, cache_v, k_scale, v_scale, k_new, v_new


class TestKvAppendParity:
    # NOTE: kv_append ALIASES (donates) the cache operands — every test
    # materializes a second identically-seeded state for the reference
    # path / originals instead of reusing the donated arrays.

    @pytest.mark.parametrize("layer", [0, 2])
    def test_matches_xla_path(self, layer):
        state = make_state(layer)
        # positions spread across scale blocks, incl. block edges
        positions = jnp.asarray(
            [0, 1, 31, 32, 33, 62, 63, 40][:B], jnp.int32)
        got = kva.kv_append(*state, jnp.int32(layer), positions,
                            interpret=True)
        want = reference_append(*make_state(layer), jnp.int32(layer),
                                positions)
        # int8 payloads bit-exact; scales allow 1-ULP compilation noise
        # (interpret-mode max/div association differs from the XLA fusion)
        for g, w, name in zip(got[:2], want[:2], ("k", "v")):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w),
                                          err_msg=name)
        for g, w, name in zip(got[2:], want[2:], ("ks", "vs")):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=1e-6, err_msg=name)

    def test_untouched_rows_survive(self):
        got = kva.kv_append(*make_state(7), jnp.int32(1),
                            jnp.full((B,), 10, jnp.int32), interpret=True)
        state = make_state(7)  # pristine copy for comparison
        # other layers + other positions bit-identical
        np.testing.assert_array_equal(np.asarray(got[0][0]),
                                      np.asarray(state[0][0]))
        np.testing.assert_array_equal(np.asarray(got[0][1, :, 11:]),
                                      np.asarray(state[0][1, :, 11:]))
        np.testing.assert_array_equal(np.asarray(got[2][2]),
                                      np.asarray(state[2][2]))
        # scale neighbours within the written 32-block survive
        np.testing.assert_array_equal(np.asarray(got[2][1, :, :, :10]),
                                      np.asarray(state[2][1, :, :, :10]))
        np.testing.assert_array_equal(np.asarray(got[2][1, :, :, 11:]),
                                      np.asarray(state[2][1, :, :, 11:]))

    def test_out_of_range_position_clamps(self):
        """A stale slot at capacity must not crash; it writes the last row
        (garbage-on-garbage, re-initialized by the next insert)."""
        positions = jnp.asarray([T, T + 5] + [4] * (B - 2), jnp.int32)
        got = kva.kv_append(*make_state(3), jnp.int32(0), positions,
                            interpret=True)
        state = make_state(3)  # pristine copy
        # slot 2..: normal write at 4; slots 0-1: row T-1 written
        want_q, _ = quantize_kv(state[4][0:1][:, None])
        np.testing.assert_array_equal(np.asarray(got[0][0, 0, T - 1]),
                                      np.asarray(want_q[0, 0]))

    def test_supports_gate(self, monkeypatch):
        monkeypatch.setenv("SYMMETRY_KV_APPEND", "1")
        assert not kva.supports(64, 128, "cpu", sharded=False)
        assert not kva.supports(64, 128, "tpu", sharded=True)
        assert not kva.supports(64, 64, "tpu", sharded=False)
        assert kva.supports(640, 128, "tpu", sharded=False)
        # measured slower via the partial trailing scale block (BASELINE)
        assert not kva.supports(672, 128, "tpu", sharded=False)
        assert kva.supports(64, 128, "tpu", sharded=False)  # < one block
        monkeypatch.delenv("SYMMETRY_KV_APPEND")
        # opt-in: off by default (measured HBM cost in the decode scan)
        assert not kva.supports(640, 128, "tpu", sharded=False)
