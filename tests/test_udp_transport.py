"""Native C++ udpstream transport: framing, fragmentation, multiplexing,
close semantics, and the full Noise-encrypted peer channel over UDP.

Skipped cleanly when no C++ toolchain is available to build the library.
"""

import asyncio
import os

import pytest

try:
    from symmetry_tpu.transport.udp import UdpTransport, load_library

    load_library()
    HAVE_UDP = True
except Exception:  # noqa: BLE001 — no toolchain / build failure
    HAVE_UDP = False

pytestmark = pytest.mark.skipif(not HAVE_UDP,
                                reason="udpstream library unavailable")


def run(coro):
    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(coro, 60))


def test_roundtrip_and_frame_boundaries():
    async def main():
        t = UdpTransport()
        inbox = asyncio.Queue()

        async def handler(conn):
            while True:
                f = await conn.recv()
                if f is None:
                    return
                await conn.send(b"echo:" + f)

        lst = await t.listen("udp://127.0.0.1:0", handler)
        conn = await t.dial(lst.address)
        # Distinct frames stay distinct (no coalescing/splitting).
        await conn.send(b"one")
        await conn.send(b"two")
        assert await conn.recv() == b"echo:one"
        assert await conn.recv() == b"echo:two"
        await conn.close()
        await lst.close()

    run(main())


def test_large_frame_fragmentation():
    """Frames far beyond the 1200-byte MTU segment size reassemble exactly."""
    async def main():
        t = UdpTransport()
        got = asyncio.Queue()

        async def handler(conn):
            f = await conn.recv()
            got.put_nowait(f)

        lst = await t.listen("udp://127.0.0.1:0", handler)
        conn = await t.dial(lst.address)
        payload = os.urandom(256 * 1024)  # ~220 segments
        await conn.send(payload)
        received = await asyncio.wait_for(got.get(), 30)
        assert received == payload
        await conn.close()
        await lst.close()

    run(main())


def test_many_connections_multiplexed():
    async def main():
        t = UdpTransport()

        async def handler(conn):
            f = await conn.recv()
            await conn.send(f[::-1])

        lst = await t.listen("udp://127.0.0.1:0", handler)

        async def one(i):
            conn = await t.dial(lst.address)
            msg = f"conn-{i}".encode()
            await conn.send(msg)
            out = await conn.recv()
            await conn.close()
            return out

        outs = await asyncio.gather(*[one(i) for i in range(8)])
        assert outs == [f"conn-{i}".encode()[::-1] for i in range(8)]
        await lst.close()

    run(main())


def test_clean_close_gives_eof():
    async def main():
        t = UdpTransport()
        done = asyncio.Queue()

        async def handler(conn):
            while True:
                f = await conn.recv()
                if f is None:
                    done.put_nowait("eof")
                    return

        lst = await t.listen("udp://127.0.0.1:0", handler)
        conn = await t.dial(lst.address)
        await conn.send(b"x")
        await conn.close()
        assert await asyncio.wait_for(done.get(), 20) == "eof"
        await lst.close()

    run(main())


def test_dial_nobody_fails():
    async def main():
        t = UdpTransport()
        with pytest.raises(ConnectionError):
            await t.dial("udp://127.0.0.1:9")  # discard port — no listener

    run(main())


def test_noise_peer_channel_over_udp():
    """The full encrypted peer handshake + message exchange over the native
    transport — what production uses (SURVEY layers A-E stacked)."""
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.network.peer import Peer
    from symmetry_tpu.protocol.keys import MessageKey

    async def main():
        t = UdpTransport()
        server_ident = Identity.from_name("udp-srv")
        client_ident = Identity.from_name("udp-cli")
        got = asyncio.Queue()

        async def handler(conn):
            peer = await Peer.connect(conn, server_ident, initiator=False)
            msg = await peer.recv()
            got.put_nowait((msg.key, msg.data))
            await peer.send(MessageKey.PONG, {"ok": True})

        lst = await t.listen("udp://127.0.0.1:0", handler)
        conn = await t.dial(lst.address)
        peer = await Peer.connect(conn, client_ident, initiator=True,
                                  expected_remote_key=server_ident.public_key)
        await peer.send(MessageKey.PING, {"n": 1})
        key, data = await asyncio.wait_for(got.get(), 20)
        assert key == MessageKey.PING and data == {"n": 1}
        reply = await peer.recv()
        assert reply.key == MessageKey.PONG
        await peer.close()
        await lst.close()

    run(main())
