"""End-to-end: client → server → provider → backend → streamed response.

The full three-role system (SURVEY §7 stage 3 'minimum slice') running as
asyncio nodes over the in-memory transport — no sockets, no TPU.
"""

import asyncio

import pytest

from symmetry_tpu.client.client import ClientError, SymmetryClient
from symmetry_tpu.identity import Identity
from symmetry_tpu.provider.backends.echo import EchoBackend
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.server.broker import SymmetryServer
from symmetry_tpu.transport.memory import MemoryTransport


def run(coro):
    return asyncio.new_event_loop().run_until_complete(asyncio.wait_for(coro, 30))


def make_config(server_key_hex, *, name="prov-1", model="echo-model", public=True,
                **extra):
    return ConfigManager(config={
        "name": name,
        "public": public,
        "serverKey": server_key_hex,
        "modelName": model,
        "apiProvider": "echo",
        "dataCollectionEnabled": False,
        **extra,
    })


async def start_system(hub, *, model="echo-model", providers=1, ping_interval=30.0):
    server_ident = Identity.from_name("e2e-server")
    server = SymmetryServer(server_ident, hub, ping_interval_s=ping_interval)
    await server.start("mem://server")
    provs = []
    for i in range(providers):
        cfg = make_config(server_ident.public_hex, name=f"prov-{i}", model=model)
        p = SymmetryProvider(
            cfg, transport=hub, backend=EchoBackend(),
            identity=Identity.from_name(f"prov-{i}"),
            server_address="mem://server",
        )
        await p.start(f"mem://prov-{i}")
        await p.wait_registered()
        provs.append(p)
    return server, provs, server_ident


def test_full_flow_stream():
    async def main():
        hub = MemoryTransport()
        server, provs, server_ident = await start_system(hub)
        client = SymmetryClient(Identity.from_name("cli"), hub)
        details = await client.request_provider(
            "mem://server", server_ident.public_key, "echo-model"
        )
        assert details.model_name == "echo-model"
        assert details.address == "mem://prov-0"
        session = await client.connect(details)
        deltas = []
        async for d in session.chat([{"role": "user", "content": "hello distributed world"}]):
            deltas.append(d)
        assert "".join(deltas) == "hello distributed world"
        assert len(deltas) == 3  # streamed word-by-word, not one blob
        # Second request over the same session works.
        text = await session.chat_text([{"role": "user", "content": "again"}])
        assert text == "again"
        # Clients can query the provider's serving snapshot in-session.
        stats = await session.stats()
        assert stats["requests"] == 2
        assert stats["ttft_s"]["count"] == 2
        await session.close()
        for p in provs:
            await p.stop()
        await server.stop()

    run(main())


def test_no_provider_for_model():
    async def main():
        hub = MemoryTransport()
        server, provs, server_ident = await start_system(hub)
        client = SymmetryClient(Identity.from_name("cli2"), hub)
        with pytest.raises(ClientError, match="no provider"):
            await client.request_provider(
                "mem://server", server_ident.public_key, "gpt-17"
            )
        for p in provs:
            await p.stop()
        await server.stop()

    run(main())


def test_model_routing_two_providers():
    async def main():
        hub = MemoryTransport()
        server_ident = Identity.from_name("router-server")
        server = SymmetryServer(server_ident, hub)
        await server.start("mem://server")
        names = {}
        for model in ("llama3:8b", "mistral-7b"):
            cfg = make_config(server_ident.public_hex, name=f"p-{model}", model=model)
            p = SymmetryProvider(cfg, transport=hub, backend=EchoBackend(),
                                 identity=Identity.from_name(f"p-{model}"),
                                 server_address="mem://server")
            await p.start(f"mem://p-{model}")
            await p.wait_registered()
            names[model] = p
        client = SymmetryClient(Identity.from_name("cli3"), hub)
        # Routing: each model resolves to its own provider (BASELINE config 4).
        for model in ("llama3:8b", "mistral-7b"):
            details = await client.request_provider(
                "mem://server", server_ident.public_key, model
            )
            assert details.address == f"mem://p-{model}"
        models = await client.list_models("mem://server", server_ident.public_key)
        assert {m["model_name"] for m in models} == {"llama3:8b", "mistral-7b"}
        for p in names.values():
            await p.stop()
        await server.stop()

    run(main())


def test_session_token_required_and_enforced():
    async def main():
        hub = MemoryTransport()
        server, provs, server_ident = await start_system(hub)
        # A client that skips the server and fabricates no token must be refused.
        rogue = SymmetryClient(Identity.from_name("rogue"), hub)
        session = await rogue.connect_direct("mem://prov-0", model_name="echo-model")
        with pytest.raises(ClientError, match="session"):
            async for _ in session.chat([{"role": "user", "content": "free lunch"}]):
                pass
        await session.close()
        # With a legitimate token it works.
        legit = SymmetryClient(Identity.from_name("legit"), hub)
        details = await legit.request_provider(
            "mem://server", server_ident.public_key, "echo-model"
        )
        s2 = await legit.connect(details)
        assert await s2.chat_text([{"role": "user", "content": "paid lunch"}]) == "paid lunch"
        await s2.close()
        # A token minted for one client must not work for another (binding).
        thief = SymmetryClient(Identity.from_name("thief"), hub)
        stolen = await thief.connect(details)  # same details, different identity
        with pytest.raises(ClientError, match="session"):
            async for _ in stolen.chat([{"role": "user", "content": "stolen"}]):
                pass
        await stolen.close()
        for p in provs:
            await p.stop()
        await server.stop()

    run(main())


def test_private_provider_direct_connection():
    async def main():
        hub = MemoryTransport()
        ident = Identity.from_name("private-prov")
        cfg = make_config("ab" * 32, name="private", public=False)
        p = SymmetryProvider(cfg, transport=hub, backend=EchoBackend(),
                             identity=ident)
        await p.start("mem://private")
        client = SymmetryClient(Identity.from_name("direct-cli"), hub)
        session = await client.connect_direct(
            "mem://private", provider_key=ident.public_key
        )
        assert await session.chat_text([{"role": "user", "content": "direct hi"}]) == "direct hi"
        await session.close()
        await p.stop()

    run(main())


def test_provider_disconnect_marks_offline():
    async def main():
        hub = MemoryTransport()
        server, provs, server_ident = await start_system(hub)
        assert server.registry.select_provider("echo-model") is not None
        await provs[0].stop()  # graceful leave
        await asyncio.sleep(0.1)
        assert server.registry.select_provider("echo-model") is None
        await server.stop()

    run(main())


def test_data_collection_writes_conversation(tmp_path):
    async def main():
        hub = MemoryTransport()
        server_ident = Identity.from_name("dc-server")
        server = SymmetryServer(server_ident, hub)
        await server.start("mem://server")
        cfg = make_config(server_ident.public_hex, name="dc-prov",
                          dataCollectionEnabled=True, path=str(tmp_path))
        p = SymmetryProvider(cfg, transport=hub, backend=EchoBackend(),
                             identity=Identity.from_name("dc-prov"),
                             server_address="mem://server")
        await p.start("mem://dc-prov")
        await p.wait_registered()
        client = SymmetryClient(Identity.from_name("dc-cli"), hub)
        details = await client.request_provider(
            "mem://server", server_ident.public_key, "echo-model"
        )
        session = await client.connect(details)
        await session.new_conversation()
        await session.chat_text([{"role": "user", "content": "remember me"}])
        await session.close()
        await asyncio.sleep(0.2)
        files = list(tmp_path.glob("*.json"))
        assert len(files) == 1
        import json

        saved = json.loads(files[0].read_text())
        assert saved["messages"][0]["content"] == "remember me"
        assert saved["messages"][-1] == {"role": "assistant", "content": "remember me"}
        await p.stop()
        await server.stop()

    run(main())


def test_concurrent_clients_one_provider():
    async def main():
        hub = MemoryTransport()
        server, provs, server_ident = await start_system(hub)

        async def one_client(i):
            c = SymmetryClient(Identity.from_name(f"cc-{i}"), hub)
            details = await c.request_provider(
                "mem://server", server_ident.public_key, "echo-model"
            )
            s = await c.connect(details)
            text = await s.chat_text([{"role": "user", "content": f"msg {i}"}])
            await s.close()
            return text

        results = await asyncio.gather(*(one_client(i) for i in range(8)))
        assert results == [f"msg {i}" for i in range(8)]
        for p in provs:
            await p.stop()
        await server.stop()

    run(main())


def test_abandoned_stream_is_cancelled_not_poisoning():
    """Breaking out of a chat mid-stream cancels it provider-side
    (inferenceCancel by requestId) and the SAME session keeps working —
    the next chat gets the NEW completion, never the old stream's
    stragglers (those are dropped by the demultiplexing reader). This
    replaces the pre-multiplexing behavior where one abandoned stream
    desynced the whole session."""
    async def main():
        hub = MemoryTransport()
        server, provs, server_ident = await start_system(hub)
        client = SymmetryClient(Identity.from_name("cli-a"), hub)
        details = await client.request_provider(
            "mem://server", server_ident.public_key, "echo-model")
        session = await client.connect(details)
        agen = session.chat(
            [{"role": "user", "content": "one two three four"}])
        first = await agen.__anext__()
        assert first
        await agen.aclose()  # abandon mid-stream
        text = await session.chat_text(
            [{"role": "user", "content": "again"}])
        assert "again" in text  # echo backend: the NEW request's content
        assert "three" not in text  # and none of the old stream's
        await session.close()
        for p in provs:
            await p.stop()
        await server.stop()

    run(main())


def test_concurrent_chats_one_session_multiplex():
    """Two chats launched CONCURRENTLY on one session interleave on the
    wire and each receives its own completion (requestId routing)."""
    async def main():
        hub = MemoryTransport()
        server, provs, server_ident = await start_system(hub)
        client = SymmetryClient(Identity.from_name("cli-mux"), hub)
        details = await client.request_provider(
            "mem://server", server_ident.public_key, "echo-model")
        session = await client.connect(details)
        a, b = await asyncio.gather(
            session.chat_text([{"role": "user", "content": "alpha"}]),
            session.chat_text([{"role": "user", "content": "bravo"}]))
        assert "alpha" in a and "bravo" not in a
        assert "bravo" in b and "alpha" not in b
        await session.close()
        for p in provs:
            await p.stop()
        await server.stop()

    run(main())


def test_per_peer_concurrency_cap():
    """One peer's request flood is rejected past maxConcurrentRequests;
    other peers are unaffected."""
    async def main():
        hub = MemoryTransport()
        server_ident = Identity.from_name("cap-server")
        server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
        await server.start("mem://server")
        cfg = make_config(server_ident.public_hex, name="cap-prov",
                          model="echo-model")
        cfg._config["maxConcurrentRequests"] = 2
        # slow backend: streams stay in flight while the flood arrives, so
        # rejections are GUARANTEED (a fast echo could drain between
        # sends and pass this test without exercising the cap)
        from tests.test_failover import SlowBackend

        provider = SymmetryProvider(cfg, transport=hub,
                                    identity=Identity.from_name("cap-prov"),
                                    backend=SlowBackend(delay=0.05, n=10),
                                    server_address="mem://server")
        await provider.start("mem://cap-prov")
        await provider.wait_registered()
        client = SymmetryClient(Identity.from_name("cap-cli"), hub)
        details = await client.request_provider(
            "mem://server", server_ident.public_key, "echo-model")
        session = await client.connect(details)
        results = await asyncio.gather(
            *(session.chat_text([{"role": "user", "content": f"r{i}"}])
              for i in range(6)),
            return_exceptions=True)
        ok = [r for r in results if isinstance(r, str)]
        rejected = [r for r in results if isinstance(r, Exception)]
        assert ok, results  # some complete
        assert rejected, results  # and the cap actually fired
        assert all("too many concurrent" in str(r) for r in rejected)
        # a SECOND peer still works even while the first is flooding
        client2 = SymmetryClient(Identity.from_name("cap-cli2"), hub)
        other = await client2.connect(await client2.request_provider(
            "mem://server", server_ident.public_key, "echo-model"))
        assert await other.chat_text(
            [{"role": "user", "content": "hello"}])
        await other.close()
        await session.close()
        await provider.stop(drain_timeout_s=2)
        await server.stop()

    run(main())
