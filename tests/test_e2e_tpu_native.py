"""End-to-end with the TPU engine: client → server → provider → tpu_native.

BASELINE configs 2-3 in miniature: the full three-role network path serving
a real (tiny) JAX model with continuous batching, on the CPU test backend.
"""

import asyncio

import pytest

from symmetry_tpu.client.client import SymmetryClient
from symmetry_tpu.identity import Identity
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.server.broker import SymmetryServer
from symmetry_tpu.transport.memory import MemoryTransport


def run(coro):
    return asyncio.new_event_loop().run_until_complete(asyncio.wait_for(coro, 300))


def tpu_config(server_key_hex, isolation):
    return ConfigManager(config={
        "name": "tpu-prov",
        "public": True,
        "serverKey": server_key_hex,
        "modelName": "tiny:test",
        "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "tpu": {"model_preset": "tiny", "dtype": "float32",
                "max_batch_size": 4, "max_seq_len": 128,
                "prefill_buckets": [32, 64],
                # "process" exercises the production engine-host pipe
                # (engine/host.py); "inproc" the direct thread path.
                "engine_isolation": isolation},
    })


@pytest.mark.parametrize(
    "isolation",
    ["inproc",
     # the host-subprocess path recompiles the engine in a fresh process
     pytest.param("process", marks=pytest.mark.slow)])
def test_tpu_native_full_flow(isolation):
    async def main():
        hub = MemoryTransport()
        server_ident = Identity.from_name("tpu-e2e-server")
        server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
        await server.start("mem://server")

        cfg = tpu_config(server_ident.public_hex, isolation)
        provider = SymmetryProvider(
            cfg, transport=hub,
            identity=Identity.from_name("tpu-prov"),
            server_address="mem://server",
        )
        await provider.start("mem://tpu-prov")
        await provider.wait_registered()

        client = SymmetryClient(Identity.from_name("tpu-cli"), hub)
        details = await client.request_provider(
            "mem://server", server_ident.public_key, "tiny:test")
        assert details.model_name == "tiny:test"
        session = await client.connect(details)

        # Two concurrent chats through one provider: continuous batching on
        # the network path. (Tiny random weights — assert streaming works and
        # text is non-trivial, not that it's sensible.)
        async def one_chat(text):
            deltas = []
            async for d in session.chat(
                    [{"role": "user", "content": text}], max_tokens=8):
                deltas.append(d)
            return "".join(deltas)

        texts = await asyncio.gather(one_chat("hello"), one_chat("world"))
        assert all(isinstance(t, str) for t in texts)

        await session.close()
        await provider.stop()
        await server.stop()

    run(main())
