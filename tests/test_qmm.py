"""w8a8 native-int8 matmul kernel (ops/qmm.py), Pallas interpret mode.

The integer part of the kernel is exact: s8×s8 products accumulated in
s32 must equal the same integer matmul computed in numpy, so the kernel
is tested against that bit-exact reference (scales are f32 — compared
with float tolerance), and separately against the dense matmul within
the activation-quantization error bound.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.ops.qmm import (
    MIN_ROWS,
    quantize_rows,
    supports,
    w8a8_matmul,
)
from symmetry_tpu.ops.quant import quantize


@pytest.fixture(scope="module")
def case():
    key = jax.random.key(0)
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (64, 128), jnp.float32)
    w = jax.random.normal(kw, (128, 256), jnp.float32) * 0.05
    return x, quantize(w), w


class TestW8A8:
    def test_matches_integer_reference(self, case):
        x, wq, _ = case
        got = w8a8_matmul(x, wq.q, wq.scale, interpret=True)

        xq, xs = quantize_rows(x)
        acc = (np.asarray(xq, np.int32) @ np.asarray(wq.q, np.int32))
        want = acc.astype(np.float32) * np.asarray(xs) * np.asarray(
            wq.scale)[None, :]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-5)

    def test_close_to_dense(self, case):
        x, wq, w = case
        got = np.asarray(w8a8_matmul(x, wq.q, wq.scale, interpret=True))
        want = np.asarray(x) @ np.asarray(w)
        # both weight and activation are 8-bit: ~1% relative on a
        # 128-deep contraction
        err = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
        assert err < 0.02, err

    def test_out_dtype(self, case):
        x, wq, _ = case
        got = w8a8_matmul(x.astype(jnp.bfloat16), wq.q, wq.scale,
                          interpret=True)
        assert got.dtype == jnp.bfloat16

    def test_block_fallback_shapes(self):
        """Shapes needing the smaller block candidates still tile."""
        x = jnp.ones((MIN_ROWS, 192), jnp.float32)  # K=192 -> bk=64
        w = quantize(jnp.ones((192, 320), jnp.float32))  # N=320 -> bn=64
        got = w8a8_matmul(x, w.q, w.scale, interpret=True)
        assert got.shape == (MIN_ROWS, 320)

    def test_supports_gate(self):
        assert supports(128, 4096, 14336, "tpu")
        assert supports(128, 4096, 128256, "tpu")  # llama3 lm_head
        assert not supports(128, 4096, 14336, "cpu")
        assert not supports(MIN_ROWS - 1, 4096, 14336, "tpu")
        assert not supports(128, 100, 14336, "tpu")   # K untileable
        assert not supports(128, 4096, 258, "tpu")    # N untileable
