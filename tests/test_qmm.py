"""Quantized matmul kernels (ops/qmm.py), Pallas interpret mode.

W8A8: the integer part of the kernel is exact — s8×s8 products
accumulated in s32 must equal the same integer matmul computed in numpy,
so the kernel is tested against that bit-exact reference (scales are
f32 — compared with float tolerance), and separately against the dense
matmul within the activation-quantization error bound.

W8A16 (tpu.fused_dequant): the fused tile-dequant kernel is specified to
compute EXACTLY qmatmul's reference semantics — (x @ q) accumulated f32,
per-output-channel scale in the epilogue, cast to the activation dtype —
so it is pinned against the mixed dot across every trunk matmul shape
family (wide/narrow N, GQA head dims, ragged K needing small-tile
fallback, single-row and MIN_ROWS edges), and the engine-level contract
(greedy decode token-identical with the knob on vs off, zero
steady-state recompiles after warmup) is enforced on the tiny preset.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.ops.qmm import (
    MIN_ROWS,
    pick_w8a16_block,
    quantize_rows,
    supports,
    w8a8_matmul,
    w8a16_matmul,
    w8a16_supports,
)
from symmetry_tpu.ops.quant import (
    PackedQuantizedTensor,
    QuantizedTensor,
    pack_quantized,
    qmatmul,
    quantize,
    unpack_quantized,
)


@pytest.fixture(scope="module")
def case():
    key = jax.random.key(0)
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (64, 128), jnp.float32)
    w = jax.random.normal(kw, (128, 256), jnp.float32) * 0.05
    return x, quantize(w), w


class TestW8A8:
    def test_matches_integer_reference(self, case):
        x, wq, _ = case
        got = w8a8_matmul(x, wq.q, wq.scale, interpret=True)

        xq, xs = quantize_rows(x)
        acc = (np.asarray(xq, np.int32) @ np.asarray(wq.q, np.int32))
        want = acc.astype(np.float32) * np.asarray(xs) * np.asarray(
            wq.scale)[None, :]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-5)

    def test_close_to_dense(self, case):
        x, wq, w = case
        got = np.asarray(w8a8_matmul(x, wq.q, wq.scale, interpret=True))
        want = np.asarray(x) @ np.asarray(w)
        # both weight and activation are 8-bit: ~1% relative on a
        # 128-deep contraction
        err = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
        assert err < 0.02, err

    def test_out_dtype(self, case):
        x, wq, _ = case
        got = w8a8_matmul(x.astype(jnp.bfloat16), wq.q, wq.scale,
                          interpret=True)
        assert got.dtype == jnp.bfloat16

    def test_block_fallback_shapes(self):
        """Shapes needing the smaller block candidates still tile."""
        x = jnp.ones((MIN_ROWS, 192), jnp.float32)  # K=192 -> bk=64
        w = quantize(jnp.ones((192, 320), jnp.float32))  # N=320 -> bn=64
        got = w8a8_matmul(x, w.q, w.scale, interpret=True)
        assert got.shape == (MIN_ROWS, 320)

    def test_supports_gate(self):
        assert supports(128, 4096, 14336, "tpu")
        assert supports(128, 4096, 128256, "tpu")  # llama3 lm_head
        assert not supports(128, 4096, 14336, "cpu")
        assert not supports(MIN_ROWS - 1, 4096, 14336, "tpu")
        assert not supports(128, 100, 14336, "tpu")   # K untileable
        assert not supports(128, 4096, 258, "tpu")    # N untileable


# ---------------------------------------------------------------------------
# W8A16 fused-dequant kernel (tpu.fused_dequant)

# Every matmul shape family the decoder trunk routes through qmatmul, at
# CPU-testable sizes: (M, K, N) with M covering the decode slot batch,
# coalesced-prefill rows, the verify block (slots × (1+k)), and the
# single-row prefill-head edge; K/N covering wide FFN, narrow GQA kv_dim,
# the wide lm_head, and ragged dims that force the small-tile fallback.
TRUNK_SHAPES = (
    (128, 64, 64),     # wq at decode batch
    (128, 64, 32),     # wk/wv: GQA narrow N (kv_dim < lane tile)
    (128, 64, 128),    # wg/wu: FFN wide
    (128, 128, 64),    # wd: FFN contraction
    (128, 64, 512),    # lm_head: vocab-wide N
    (MIN_ROWS, 192, 320),  # ragged K and N: small-tile fallback blocks
    (1, 64, 512),      # single row (batch-1 prefill head projection)
    (2, 64, 64),       # tiny batch
    (1152, 64, 64),    # verify-block rows (128 slots × (1 + k_draft 8))
)


def _reference_qmatmul(x: np.ndarray, qt) -> np.ndarray:
    """The fused kernel's bit-exact SPEC, computed independently in
    numpy: (x @ q) in f32, per-output-channel scale, cast to x.dtype."""
    acc = x.astype(np.float32) @ np.asarray(qt.q, np.float32)
    return (acc * np.asarray(qt.scale)[None, :]).astype(x.dtype)


class TestW8A16:
    def _case(self, m, k, n, seed=0, dtype=jnp.float32):
        kx, kw = jax.random.split(jax.random.key(seed))
        x = jax.random.normal(kx, (m, k), dtype)
        w = jax.random.normal(kw, (k, n), jnp.float32) * 0.05
        return x, quantize(w)

    def test_parity_across_trunk_shapes(self):
        for m, k, n in TRUNK_SHAPES:
            x, qt = self._case(m, k, n, seed=m + k + n)
            pt = pack_quantized(qt)
            assert isinstance(pt, PackedQuantizedTensor), (m, k, n)
            got = np.asarray(w8a16_matmul(x, pt.q, pt.scale,
                                          interpret=True))
            want = _reference_qmatmul(np.asarray(x), qt)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                       err_msg=f"shape {(m, k, n)}")

    def test_matches_mixed_dot_routing(self):
        """qmatmul on the packed leaf == qmatmul on the flat leaf (the
        production routing equivalence, 2-D and 3-D activations)."""
        x, qt = self._case(16, 64, 96, seed=1)
        np.testing.assert_allclose(
            np.asarray(qmatmul(x, pack_quantized(qt))),
            np.asarray(qmatmul(x, qt)), rtol=1e-5, atol=1e-5)
        x3 = x.reshape(4, 4, 64)
        got3 = qmatmul(x3, pack_quantized(qt))
        assert got3.shape == (4, 4, 96)
        np.testing.assert_allclose(np.asarray(got3),
                                   np.asarray(qmatmul(x3, qt)),
                                   rtol=1e-5, atol=1e-5)

    def test_out_dtype_follows_activation(self):
        x, qt = self._case(8, 64, 64, seed=2, dtype=jnp.bfloat16)
        pt = pack_quantized(qt)
        got = w8a16_matmul(x, pt.q, pt.scale, interpret=True)
        assert got.dtype == jnp.bfloat16

    def test_pack_roundtrip_bit_exact(self):
        _, qt = self._case(1, 192, 320, seed=3)
        pt = pack_quantized(qt)
        rt = unpack_quantized(pt)
        assert (np.asarray(rt.q) == np.asarray(qt.q)).all()
        assert (np.asarray(rt.scale) == np.asarray(qt.scale)).all()

    def test_pack_stacked_layers(self):
        """[L, K, N] stacks pack per layer; stripping the leading dim
        (what lax.scan does) yields exactly the 2-D packed layout."""
        w = jax.random.normal(jax.random.key(4), (3, 64, 32), jnp.float32)
        qt = quantize(w)
        pt = pack_quantized(qt)
        assert pt.q.shape[0] == 3 and pt.scale.shape == (3, 32)
        per_layer = pack_quantized(
            QuantizedTensor(q=qt.q[1], scale=qt.scale[1]))
        assert (np.asarray(pt.q[1]) == np.asarray(per_layer.q)).all()

    def test_untileable_stays_flat(self):
        """Shapes the kernel can't tile keep the flat QuantizedTensor —
        the per-leaf mixed-dot fallback, never an error."""
        qt = quantize(jnp.ones((100, 96), jnp.float32))  # K=100 untileable
        assert isinstance(pack_quantized(qt), QuantizedTensor)

    def test_supports_gate(self):
        assert w8a16_supports(4096, 14336, "tpu")   # llama3 FFN
        assert w8a16_supports(4096, 128256, "tpu")  # llama3 lm_head
        assert w8a16_supports(4096, 1024, "tpu")    # GQA kv_dim
        assert not w8a16_supports(100, 14336, "tpu")  # K untileable
        assert not w8a16_supports(4096, 96, "tpu")  # N under the 128 floor
        assert w8a16_supports(64, 32, "cpu")        # tiny presets (tests)

    def test_pick_block(self):
        assert pick_w8a16_block(4096, 512) == 512
        assert pick_w8a16_block(320, 512) == 64
        assert pick_w8a16_block(100, 512) is None
        assert pick_w8a16_block(64, 512, floor=128) is None


class TestFusedDecodeEngine:
    """Engine-level contract of tpu.fused_dequant on the tiny preset."""

    def _engine(self, fused: bool, block: int = 1):
        from symmetry_tpu.engine.engine import InferenceEngine
        from symmetry_tpu.engine.tokenizer import ByteTokenizer
        from symmetry_tpu.models import init_params, preset
        from symmetry_tpu.models.llama import quantize_params

        cfg = preset("tiny")
        params = quantize_params(
            init_params(cfg, jax.random.key(0), jnp.float32))
        return InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            prefill_buckets=(16,), cache_dtype=jnp.float32,
            decode_block=block, fused_dequant=fused)

    def test_greedy_token_identical_knob_on_vs_off(self):
        """The decode-equivalence acceptance: greedy output is
        token-identical with the fused path on vs off."""
        from symmetry_tpu.engine.engine import SamplingParams

        prompt = list(b"fused parity")
        outs = {}
        for fused in (False, True):
            eng = self._engine(fused)
            first = eng.prefill_and_insert(0, prompt, SamplingParams())
            toks = [first]
            for _ in range(11):
                toks.append(int(eng.decode_step()[0]))
            outs[fused] = toks
        assert outs[True] == outs[False]

    def test_params_are_packed(self):
        eng = self._engine(True)
        layers = eng.params["layers"]
        for name in ("wq", "wk", "wv", "wo", "wg", "wu", "wd"):
            assert isinstance(layers[name], PackedQuantizedTensor), name
        assert isinstance(eng.params["lm_head"], PackedQuantizedTensor)

    def test_fused_requires_quantized_weights(self):
        from symmetry_tpu.engine.engine import EngineError, InferenceEngine
        from symmetry_tpu.engine.tokenizer import ByteTokenizer
        from symmetry_tpu.models import init_params, preset

        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        with pytest.raises(EngineError, match="quantization"):
            InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                            max_seq_len=64, prefill_buckets=(16,),
                            cache_dtype=jnp.float32, fused_dequant=True)

    def test_warmup_then_zero_steady_state_recompiles(self):
        """Warmup must cover the fused compile set completely: serving
        traffic after warmup may not grow any jit's compiled-variant
        count (a mid-traffic XLA compile is the stall warmup prevents)."""
        from symmetry_tpu.engine.engine import SamplingParams

        eng = self._engine(True, block=2)
        eng.warmup()
        baseline = eng.compile_cache_sizes()
        assert baseline["_decode"] >= 1 and baseline["_prefill"] >= 1
        eng.prefill_and_insert_many(
            [(0, list(b"hello"), SamplingParams()),
             (1, list(b"world"), SamplingParams(temperature=0.5, seed=7))])
        for _ in range(3):
            eng.decode_steps()
        assert eng.compile_cache_sizes() == baseline

    def test_weight_stream_bytes_counts_matmul_weights(self):
        eng = self._engine(True)
        want = sum(
            leaf.nbytes for leaf in jax.tree.leaves(eng.params)) \
            - eng.params["embed"].nbytes
        assert eng.weight_stream_bytes() == want
