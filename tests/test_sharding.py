"""Sharded execution on the 8-device CPU mesh (SURVEY §4 build implication):
tensor-parallel forward must compile, run, and agree with the single-device
result — the same code path the real v5e-8 uses.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from symmetry_tpu.models import (
    forward, init_cache, init_params, param_logical_axes, preset,
)
from symmetry_tpu.models.llama import cache_logical_axes
from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return build_mesh(MeshSpec(data=2, model=4))


class TestMesh:
    def test_axis_order_and_sizes(self, mesh):
        assert mesh.axis_names == ("stage", "data", "context", "expert",
                                   "model")
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "stage": 1, "data": 2, "context": 1, "expert": 1, "model": 4}

    def test_rejects_oversized(self):
        with pytest.raises(ValueError, match="devices"):
            build_mesh(MeshSpec(data=4, model=4))


class TestShardedForward:
    def test_tp_forward_matches_single_device(self, mesh):
        # tiny-mha: 4 q heads, 4 kv heads — cleanly TP-shardable on model=4
        # (plain `tiny` has kv_heads=2, not divisible by the model axis).
        cfg = preset("tiny-mha")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        cache = init_cache(cfg, 2, 16, jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 512, (2, 6)), jnp.int32)

        ref_logits, _ = forward(params, cfg, tokens, cache)

        p_shard = shardings_for(param_logical_axes(cfg), mesh)
        c_shard = shardings_for(cache_logical_axes()._asdict(), mesh)
        sharded_params = jax.device_put(params, p_shard)
        sharded_cache = jax.device_put(
            cache, type(cache)(**c_shard))
        data_in = NamedSharding(mesh, P("data"))

        # Pin the updated cache to the same layout as the input cache — the
        # engine does this too (donated KV buffers must keep their sharding).
        cache_out = type(cache)(**c_shard)
        jitted = jax.jit(lambda p, t, c: forward(p, cfg, t, c),
                         out_shardings=(None, cache_out))
        got_logits, new_cache = jitted(
            sharded_params,
            jax.device_put(tokens, NamedSharding(mesh, P("data", None))),
            sharded_cache)

        np.testing.assert_allclose(np.asarray(got_logits),
                                   np.asarray(ref_logits),
                                   rtol=1e-4, atol=1e-4)
        # Cache must stay sharded over (batch=data, kv_heads=model).
        spec = new_cache.k.sharding.spec
        assert spec == P(None, "data", None, "model", None)

    def test_param_shardings_partition_the_right_axes(self, mesh):
        cfg = preset("tiny-mha")
        shardings = shardings_for(param_logical_axes(cfg), mesh)
        assert shardings["layers"]["wq"].spec == P(None, None, "model")
        assert shardings["layers"]["wo"].spec == P(None, "model", None)
        assert shardings["layers"]["wd"].spec == P(None, "model", None)
        assert shardings["embed"].spec == P("model", None)
