"""Edge cases of the coalesced-prefill sizing grid and admission rejects.

prefill_batches_for / bucket_for are the two functions every admission
decision routes through; their boundary behavior decides whether a
runtime dispatch can ever SELECT a batch shape warmup never compiled
(the mid-traffic-XLA-compile stall) or a prompt can slip past the
largest bucket. Covers:

  - a batch wider than max_slots is excluded from the grid
  - budget < bucket still yields batch 1 (a bucket is always servable)
  - an over-largest-bucket prompt raises EngineError, and inside an
    admission group it is rejected PER-REQUEST — the rest of the group
    still streams
"""

import threading

import jax
import jax.numpy as jnp
import pytest

from symmetry_tpu.engine.engine import (
    EngineError,
    InferenceEngine,
    SamplingParams,
)
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import init_params, preset


@pytest.fixture(scope="module")
def setup():
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    return cfg, params


def make_engine(cfg, params, *, slots=2, buckets=(16, 32), budget=None):
    return InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=slots, max_seq_len=64,
        prefill_buckets=buckets, cache_dtype=jnp.float32,
        prefill_token_budget=budget)


class TestPrefillBatchGrid:
    def test_batch_wider_than_max_slots_excluded(self, setup):
        """A 16-wide batch fits the token budget at the 16 bucket, but an
        engine with 2 slots must never offer it: runtime selection would
        hit a shape warmup never compiled."""
        cfg, params = setup
        engine = make_engine(cfg, params, slots=2, budget=2048)
        for bucket in engine.prefill_buckets:
            allowed = engine.prefill_batches_for(bucket)
            assert all(b == 1 or b <= engine.max_slots for b in allowed), \
                (bucket, allowed)
        assert engine.prefill_batches_for(16) == (1, 2)

    def test_budget_below_bucket_still_yields_batch_one(self, setup):
        """budget < bucket must clamp to the bucket (batch 1), not to an
        empty tuple — every bucket is always servable one prompt at a
        time."""
        cfg, params = setup
        engine = make_engine(cfg, params, slots=8, budget=8)
        assert engine.prefill_batches_for(16) == (1,)
        assert engine.prefill_batches_for(32) == (1,)

    def test_batches_ascending_and_contain_one(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, slots=8, budget=64)
        for bucket in engine.prefill_buckets:
            allowed = engine.prefill_batches_for(bucket)
            assert allowed[0] == 1
            assert list(allowed) == sorted(allowed)

    def test_bucket_for_boundaries(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params)
        assert engine.bucket_for(1) == 16
        assert engine.bucket_for(16) == 16
        assert engine.bucket_for(17) == 32
        assert engine.bucket_for(32) == 32

    def test_over_largest_bucket_raises(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params)
        with pytest.raises(EngineError, match="exceeds the largest"):
            engine.bucket_for(33)
        with pytest.raises(EngineError, match="exceeds the largest"):
            engine.prefill_and_insert(0, list(range(40)), SamplingParams())


class TestPerRequestRejection:
    def test_oversized_prompt_rejected_per_request_not_per_group(
            self, setup):
        """An admission group mixing an over-bucket prompt with valid
        ones: the oversized request gets its own error event and every
        other member of the group streams to completion."""
        cfg, params = setup
        engine = make_engine(cfg, params, slots=4)
        sched = Scheduler(engine, debug_invariants=True)
        prompts = [list(b"fits fine"), list(range(40)), list(b"also ok")]
        results = {i: [] for i in range(len(prompts))}
        done = {i: threading.Event() for i in range(len(prompts))}
        for i, ids in enumerate(prompts):
            def emit(ev, i=i):
                results[i].append(ev)
                if ev.done:
                    done[i].set()
            sched.submit(GenRequest(prompt_ids=ids,
                                    sampling=SamplingParams(),
                                    max_new_tokens=4, emit=emit, id=f"r{i}"))
        sched.start()
        for ev in done.values():
            assert ev.wait(120)
        sched.stop()
        assert results[1][-1].finish_reason == "error"
        assert "exceeds the largest" in results[1][-1].error
        for i in (0, 2):
            assert results[i][-1].finish_reason in ("stop", "length")
            assert results[i][-1].tokens_generated >= 1
        # the oversized request's slot went back to the pool
        assert sched.occupancy == 0
        assert sorted(sched._free) == [0, 1, 2, 3]
