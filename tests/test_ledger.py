"""symledger conservation and waste accounting (engine/ledger.py).

The ledger's correctness pin is CONSERVATION: every device second the
scheduler's own dispatch walls measure (admit_s + adopt_s + chunk_s +
sync_s) lands in exactly one request's `device_s` — or, for a block
sync whose every lane went stale, in the `unattributed` bucket — so
the per-request sum reconstructs the fleet total within 5%. The mixed
white-box run below drives every booking path on a fake engine (no
JAX, no threads — the test_scheduler_emit.py pattern): batched prefill,
radix-hit cached admission (saved_s), chunked prefill, a chunked
prefill killed mid-flight (killed_prefill), a speculative verify with
rejected drafts (spec_rejected), a mid-decode cancel (cancelled), a
deadline shed (deadline_shed, zero device by construction), and an
all-stale block (unattributed).

resume_discarded is booked relay-side (tpu_native prices deduped
resume tokens at the request's own decode rate); that module needs
`cryptography`, absent here, so the class is pinned at the ledger
level in the unit tests instead.

Disabled mode (tpu.ledger=false) is the overhead contract: track()
returns None, every booking site is one `is not None` branch, no entry
is ever allocated, no costs ride the events, and no ledger block rides
stats().
"""

import time

import numpy as np

from symmetry_tpu.engine.engine import SamplingParams
from symmetry_tpu.engine.ledger import LedgerEntry, RequestLedger
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer

# Large enough that perf_counter resolution noise cannot move a phase
# attribution by anything near the 5% conservation bound.
DISPATCH_SLEEP = 0.002

HIT_LEN = 16


class FakeHit:
    """The prefix_lookup handle contract _place_group consumes."""

    def __init__(self, length=HIT_LEN):
        self.length = length
        self.group_key = ("radix-node", length)
        self.released = 0

    def release(self):
        self.released += 1


class FakeJob:
    def __init__(self, slot):
        self.slot = slot
        self.chunks = 0


class LazyBlock:
    """A device-side token block: the scheduler's np.asarray sync
    blocks on it, so the sync wall the ledger apportions is real."""

    def __init__(self, arr):
        self.arr = arr

    def __array__(self, dtype=None, copy=None):
        time.sleep(DISPATCH_SLEEP)
        return self.arr

    @property
    def shape(self):
        return self.arr.shape


class FakeEngine:
    """Scheduler-facing engine with every admission path the ledger
    prices: batched prefill, cached (radix-hit) prefill, and chunked
    prefill. Dispatches sleep a fixed wall so attribution rates are
    well above timer noise."""

    def __init__(self, slots=8, block=4, capacity=4096,
                 buckets=(32, 128)):
        self.max_slots = slots
        self.decode_block = block
        self.slot_capacity = capacity
        self.tokenizer = ByteTokenizer()
        self.prefill_buckets = buckets
        self.prefix_align = HIT_LEN
        self.dispatches = 0
        self.released: list[int] = []

    def bucket_for(self, n):
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket")

    def prefill_batches_for(self, bucket):
        return (8,)

    # Radix hits: prompts starting with 16 "H" bytes share a cached
    # prefix of that length.
    def prefix_lookup(self, ids):
        if ids[:HIT_LEN] == [ord("H")] * HIT_LEN and len(ids) > HIT_LEN:
            return FakeHit()
        return None

    def wants_chunked(self, n):
        return n >= 64

    def start_chunked_prefill(self, slot, ids, sampling, hit=None):
        return FakeJob(slot)

    def advance_chunked_prefill(self, job):
        time.sleep(DISPATCH_SLEEP)
        job.chunks += 1
        return ord("A") if job.chunks >= 2 else None

    def prefill_and_insert(self, slot, ids, sampling):
        time.sleep(DISPATCH_SLEEP)
        return ord("A")

    def prefill_and_insert_many(self, group):
        time.sleep(DISPATCH_SLEEP)
        return [ord("A")] * len(group)

    def prefill_and_insert_cached(self, group, hit):
        time.sleep(DISPATCH_SLEEP)
        return [ord("A")] * len(group)

    def decode_steps_dispatch(self):
        self.dispatches += 1
        return LazyBlock(np.full(
            (self.decode_block, self.max_slots), ord("b"),
            dtype=np.int32))

    def release_slot(self, slot):
        self.released.append(slot)

    def slot_length(self, slot):
        return 0


def submit(sched, rid, prompt_ids, max_new=100, cancelled=None,
           deadline_at=None):
    sched.submit(GenRequest(
        prompt_ids=list(prompt_ids), sampling=SamplingParams(),
        max_new_tokens=max_new, emit=lambda ev: None,
        cancelled=cancelled or (lambda: False), id=rid,
        deadline_at=deadline_at))


def finals_of(batches):
    return {req.id: ev for batch in batches for req, ev in batch
            if ev.done}


class TestConservation:
    """Mixed traffic, then the books must balance."""

    def _drive_mixed(self, ledger_enabled=True):
        """One deterministic mixed-traffic run; returns (sched,
        batches, engine). Finish census: r0/rhit "length", r1
        "cancelled" mid-decode, rchunk "cancelled" mid-prefill
        (killed_prefill), rchunk2 "length", rlate "expired"."""
        eng = FakeEngine()
        batches: list = []
        sched = Scheduler(eng, emit_batch=batches.append,
                          prefill_chunks_per_block=1,
                          ledger_enabled=ledger_enabled)
        cancel_r1: list = []
        cancel_chunk: list = []
        submit(sched, "r0", b"plain zero", max_new=9)
        submit(sched, "r1", b"plain one", max_new=100,
               cancelled=lambda: bool(cancel_r1))
        submit(sched, "rhit", [ord("H")] * HIT_LEN + list(b"suffix"),
               max_new=9)
        submit(sched, "rchunk", b"L" * 64, max_new=100,
               cancelled=lambda: bool(cancel_chunk))
        submit(sched, "rchunk2", b"M" * 64, max_new=5)
        sched._admit_new()
        sched._flush_events()
        # Chunked prefills, one chunk per pass (chunks_per_block=1,
        # FIFO head-first): rchunk runs one chunk, is cancelled before
        # its second — the accumulated chunk wall becomes
        # killed_prefill waste — then rchunk2 runs its two and
        # activates.
        sched._advance_prefills()          # rchunk chunk 1
        cancel_chunk.append(True)
        sched._advance_prefills()          # rchunk killed; rchunk2 chunk 1
        sched._advance_prefills()          # rchunk2 chunk 2 -> activates
        sched._flush_events()
        assert {a.req.id for a in sched._slots.values()} == {
            "r0", "r1", "rhit", "rchunk2"}
        # Block 1: four live lanes split the sync wall; rchunk2
        # (activation token + 4) exhausts max_new=5 and finishes.
        snap1 = dict(sched._slots)
        toks1 = eng.decode_steps_dispatch()
        sched._process_pending(
            ("decode_block", toks1, snap1, time.monotonic(), None))
        sched._flush_events()
        # Verify block: r0's lane drafted 3 and kept 1 (2 rejected
        # drafts -> spec_rejected share), r1's drafted 3 and kept all.
        slot_of = {a.req.id: s for s, a in sched._slots.items()}
        n_draft = np.zeros(eng.max_slots, dtype=np.int64)
        n_emit = np.ones(eng.max_slots, dtype=np.int64)
        n_draft[slot_of["r0"]], n_emit[slot_of["r0"]] = 3, 2
        n_draft[slot_of["r1"]], n_emit[slot_of["r1"]] = 3, 4
        snap_v = dict(sched._slots)
        sched._process_pending(
            ("verify", eng.decode_steps_dispatch(), snap_v,
             time.monotonic(), (n_emit, n_draft, 6)))
        sched._flush_events()
        # Block 3: r1's cancel lands with the block in flight — its
        # lane share books device AND cancelled waste; r0/rhit finish
        # by length.
        cancel_r1.append(True)
        snap3 = dict(sched._slots)
        sched._process_pending(
            ("decode_block", eng.decode_steps_dispatch(), snap3,
             time.monotonic(), None))
        sched._flush_events()
        assert not sched._slots
        # All-stale block (every snap1 lane finished above): the sync
        # wall has no live owner and must book unattributed.
        sched._process_pending(
            ("decode_block", eng.decode_steps_dispatch(), snap1,
             time.monotonic(), None))
        # Deadline shed: zero device seconds, class still booked.
        submit(sched, "rlate", b"too late",
               deadline_at=time.monotonic() - 0.01)
        sched._admit_new()
        sched._flush_events()
        return sched, batches, eng

    def test_device_seconds_conserve_within_5pct(self):
        sched, batches, _eng = self._drive_mixed()
        m = sched.metrics
        rhs = (m["admit_s"] + m["adopt_s"] + m["chunk_s"] + m["sync_s"])
        led = sched.stats()["ledger"]
        lhs = led["device_total_s"]
        assert rhs > 0
        assert abs(lhs - rhs) <= max(0.05 * rhs, 1e-4), (lhs, rhs)
        # Per-request reconstruction: with every entry closed, the ring
        # blocks plus the unattributed residue ARE the fleet total.
        assert led["live"] == 0 and led["finished"] == 6
        ring_sum = sum(b["device_total_s"] for b in led["ring"])
        unattr = led["device_s"].get("unattributed", 0.0)
        assert unattr > 0  # the all-stale block really had no owner
        assert abs((ring_sum + unattr) - lhs) <= 1e-3

    def test_every_waste_class_booked(self):
        sched, batches, _eng = self._drive_mixed()
        led = sched.stats()["ledger"]
        assert {"cancelled", "killed_prefill", "spec_rejected",
                "deadline_shed"} <= set(led["wasted_s"])
        assert led["wasted_s"]["deadline_shed"] == 0.0
        assert led["wasted_s"]["cancelled"] > 0
        assert led["wasted_s"]["killed_prefill"] > 0
        assert led["wasted_s"]["spec_rejected"] > 0
        assert led["wasted_tokens"]["spec_rejected"] == 2
        finals = finals_of(batches)
        # killed_prefill reclassifies the whole accumulated chunk wall.
        kp = finals["rchunk"].costs
        assert kp["finish"] == "cancelled"
        assert kp["wasted_s"]["killed_prefill"] > 0
        assert abs(kp["wasted_s"]["killed_prefill"]
                   - kp["device_s"]["chunk"]) <= 1e-5
        # The mid-decode cancel wasted exactly its final block share.
        cc = finals["r1"].costs
        assert cc["wasted_s"]["cancelled"] > 0
        assert cc["wasted_tokens"]["cancelled"] == 4
        by = led["by_finish"]
        assert {"length", "cancelled", "expired"} <= set(by)

    def test_costs_ride_every_terminal_event(self):
        sched, batches, _eng = self._drive_mixed()
        finals = finals_of(batches)
        assert set(finals) == {"r0", "r1", "rhit", "rchunk", "rchunk2",
                               "rlate"}
        for rid, ev in finals.items():
            costs = ev.costs
            assert isinstance(costs, dict), rid
            assert costs["finish"] == ev.finish_reason, rid
            assert costs["source"] == "blocked", rid
            assert costs["queue_s"] >= 0.0, rid
        # Streaming finishes attributed real device time; the shed one
        # attributed none.
        assert finals["r0"].costs["device_total_s"] > 0
        assert finals["rlate"].costs["device_total_s"] == 0
        assert finals["r0"].costs["tokens"] > 0
        # The radix hit priced its avoided prefix at the admitting
        # dispatch's own rate.
        hit = finals["rhit"].costs
        assert hit["saved_s"] > 0 and hit["saved_tokens"] == HIT_LEN
        led = sched.stats()["ledger"]
        assert led["saved_tokens"] == HIT_LEN
        assert led["tokens_per_device_s"] > 0

    def test_disabled_mode_books_nothing(self):
        """tpu.ledger=false: the identical run allocates zero entries,
        ships zero cost blocks, and stats() carries no ledger rider —
        the overhead contract behind the one guarded branch."""
        sched, batches, _eng = self._drive_mixed(ledger_enabled=False)
        assert sched.ledger.enabled is False
        assert sched.ledger.track("x") is None
        assert sched.ledger._live == 0 and sched.ledger._finished == 0
        assert not sched.ledger._ring
        finals = finals_of(batches)
        assert set(finals) == {"r0", "r1", "rhit", "rchunk", "rchunk2",
                               "rlate"}
        assert all(ev.costs is None for ev in finals.values())
        assert "ledger" not in sched.stats()

    def test_disabled_mode_overhead_guard(self):
        """The disabled run does strictly less work than the enabled
        one on identical traffic — a generous wall bound (pure fake
        dispatches dominated by fixed sleeps) that would only trip if
        the disabled path grew real per-token work."""
        t0 = time.perf_counter()
        self._drive_mixed(ledger_enabled=True)
        on_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        self._drive_mixed(ledger_enabled=False)
        off_s = time.perf_counter() - t0
        assert off_s <= on_s * 1.5 + 0.05, (off_s, on_s)


class TestLedgerUnit:
    def test_finish_idempotent_and_fold(self):
        led = RequestLedger()
        e = led.track("a")
        assert isinstance(e, LedgerEntry)
        e.book_device("decode", 0.5, tokens=10)
        e.book_queue(0.2)
        e.book_queue(0.1)  # set-not-add: the re-pick is the true wait
        block = e.finish("stop")
        assert block["finish"] == "stop"
        assert block["device_s"] == {"decode": 0.5}
        assert block["queue_s"] == 0.1
        assert e.finish("stop") is None  # second close books nothing
        stats = led.stats()
        assert stats["finished"] == 1 and stats["live"] == 0
        assert stats["by_finish"]["stop"]["tokens"] == 10
        assert stats["ring"][-1]["id"] == "a"

    def test_release_folds_without_wire_block(self):
        led = RequestLedger()
        e = led.track("h")
        e.book_device("prefill", 0.3)
        e.release("handoff")
        e.release("handoff")  # idempotent
        stats = led.stats()
        assert stats["by_finish"]["handoff"]["requests"] == 1
        assert stats["device_total_s"] == 0.3

    def test_resume_discarded_class(self):
        """The relay-side class (tpu_native prices deduped resume
        tokens at the request's decode rate) pinned at ledger level."""
        led = RequestLedger()
        e = led.track("r")
        e.book_device("decode", 1.0, tokens=20)
        e.book_wasted("resume_discarded", 0.25, 5)
        block = e.finish("stop")
        assert block["wasted_s"]["resume_discarded"] == 0.25
        assert block["wasted_tokens"]["resume_discarded"] == 5
        assert led.stats()["wasted_s"]["resume_discarded"] == 0.25

    def test_saved_at_phase_rate(self):
        led = RequestLedger()
        e = led.track("s")
        e.book_device("chunk", 1.0)  # 100-token suffix -> 10ms/token
        e.book_saved_at_phase_rate("chunk", 100, 50)
        block = e.finish("stop")
        assert abs(block["saved_s"] - 0.5) <= 1e-9
        assert block["saved_tokens"] == 50

    def test_booking_after_close_keeps_fleet_totals_only(self):
        """A late book (emit flush racing the finish) must not mutate
        the closed entry but still lands in the fleet totals, so
        conservation holds across the race."""
        led = RequestLedger()
        e = led.track("late")
        e.finish("stop")
        e.book_device("decode", 0.2)
        e.book_emit(0.1)
        assert led.device_total_s() == 0.2
        assert led.stats()["emit_s"] == 0.1
        assert led.stats()["ring"][-1]["device_total_s"] == 0.0

    def test_measured_flag_sets_probed_source(self):
        assert RequestLedger(measured=True).source == "probed"
        assert RequestLedger(measured=False).source == "blocked"

    def test_unattributed_counts_toward_conservation(self):
        led = RequestLedger()
        led.book_unattributed(0.4)
        assert led.device_total_s() == 0.4
        assert led.stats()["device_s"]["unattributed"] == 0.4
