"""Pallas ragged decode attention vs the XLA reference (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.ops.attention import gqa_attention
from symmetry_tpu.ops.decode_attention import decode_attention, supports
from symmetry_tpu.ops.quant import quantize_kv


def make_case(B=3, T=64, K=2, G=4, D=128, L=2, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    nq = K * G
    q = jax.random.normal(ks[0], (B, nq, D), dtype)
    k = jax.random.normal(ks[1], (L, B, T, K, D), dtype)
    v = jax.random.normal(ks[2], (L, B, T, K, D), dtype)
    # ragged: slot 0 nearly full, slot 1 short, slot 2 mid
    lengths = jnp.asarray([T - 3, 5, T // 2][:B], jnp.int32)
    return q, k, v, lengths


def reference(q, k_layer, v_layer, lengths, k_scale=None, v_scale=None):
    # decode: q position is the last valid entry; scales are [B, K, T]
    positions = (lengths - 1)[:, None]
    out = gqa_attention(q[:, None], k_layer, v_layer, positions, lengths,
                        k_scale=k_scale, v_scale=v_scale)
    return out[:, 0]


def to_minor(scale):
    """quantize_kv emits [L, B, T, K]; caches store position-minor [L, B, K, T]."""
    return jnp.moveaxis(scale, -1, -2)


class TestDecodeAttentionKernel:
    @pytest.mark.parametrize("layer", [0, 1])
    @pytest.mark.parametrize("block_t", [16, 32, 64])
    def test_matches_xla_reference(self, layer, block_t):
        q, k, v, lengths = make_case()
        got = decode_attention(q, k, v, jnp.int32(layer), lengths,
                               block_t=block_t, interpret=True)
        want = reference(q, k[layer], v[layer], lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_quantized_matches_folded_xla(self):
        q, k, v, lengths = make_case(seed=1)
        kq, ksc = quantize_kv(k)
        vq, vsc = quantize_kv(v)
        ksc, vsc = to_minor(ksc), to_minor(vsc)
        got = decode_attention(q, kq, vq, jnp.int32(1), lengths,
                               k_scale=ksc, v_scale=vsc,
                               block_t=32, interpret=True)
        want = reference(q, kq[1], vq[1], lengths,
                         k_scale=ksc[1], v_scale=vsc[1])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_empty_slot_no_nan(self):
        q, k, v, lengths = make_case()
        lengths = lengths.at[1].set(0)  # empty slot: garbage out, not NaN
        got = decode_attention(q, k, v, jnp.int32(0), lengths,
                               block_t=32, interpret=True)
        assert np.isfinite(np.asarray(got)).all()
        want = reference(q, k[0], v[0], jnp.maximum(lengths, 1))
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(got[2]), np.asarray(want[2]),
                                   rtol=2e-5, atol=2e-5)

    def test_single_block(self):
        q, k, v, lengths = make_case(T=32)
        got = decode_attention(q, k, v, jnp.int32(0), lengths,
                               block_t=256, interpret=True)  # clamped to T
        want = reference(q, k[0], v[0], lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_supports_gate(self):
        import dataclasses

        from symmetry_tpu.models import preset

        assert supports(preset("llama3-8b"), 8192, "tpu")
        assert not supports(preset("llama3-8b"), 8192, "cpu")
        assert not supports(preset("llama3-8b"), 2048, "tpu")  # below crossover
        assert not supports(preset("tiny"), 8192, "tpu")       # D=16
        # windowed models now route through the kernel (window-bounded
        # block range); the capacity floor still applies
        sliding = dataclasses.replace(preset("mistral-7b"), sliding_window=4096)
        assert supports(sliding, 8192, "tpu")
        assert not supports(sliding, 2048, "tpu")
        assert supports(preset("llama3-8b"), 4096 + 640, "tpu")  # 64-mult


class TestModelIntegration:
    def test_forward_decode_uses_kernel_and_matches(self, monkeypatch):
        """Full model decode with the kernel path force-enabled (interpret)
        must reproduce the XLA path token-for-token."""
        import symmetry_tpu.ops.decode_attention as da
        from symmetry_tpu.models import ModelConfig, forward, init_cache, init_params

        cfg = ModelConfig(vocab_size=256, hidden_size=128, num_layers=2,
                          num_heads=4, num_kv_heads=2, intermediate_size=256,
                          head_dim=128, rope_theta=10000.0, max_position=256)
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, 256, (2, 8)), jnp.int32)

        def decode(force_kernel):
            if force_kernel:
                monkeypatch.setattr(da, "supports", lambda *a: True)
            else:
                monkeypatch.setattr(da, "supports", lambda *a: False)
            cache = init_cache(cfg, 2, 32, jnp.float32)
            logits, cache = forward(params, cfg, prompt, cache)
            last = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            toks = [np.asarray(last)]
            for _ in range(5):
                logits, cache = forward(params, cfg, last[:, None], cache)
                last = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
                toks.append(np.asarray(last))
            return np.stack(toks)

        np.testing.assert_array_equal(decode(True), decode(False))

    def test_forward_decode_kernel_quantized_cache(self, monkeypatch):
        import symmetry_tpu.ops.decode_attention as da
        from symmetry_tpu.models import ModelConfig, forward, init_cache, init_params

        cfg = ModelConfig(vocab_size=256, hidden_size=128, num_layers=2,
                          num_heads=4, num_kv_heads=2, intermediate_size=256,
                          head_dim=128, rope_theta=10000.0, max_position=256)
        params = init_params(cfg, jax.random.key(1), jnp.float32)
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, 256, (1, 6)), jnp.int32)

        def decode(force_kernel):
            monkeypatch.setattr(da, "supports", lambda *a: force_kernel)
            cache = init_cache(cfg, 1, 32, jnp.float32, quantized=True)
            logits, cache = forward(params, cfg, prompt, cache)
            last = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            outs = [np.asarray(logits[:, -1])]
            for _ in range(3):
                logits, cache = forward(params, cfg, last[:, None], cache)
                last = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
                outs.append(np.asarray(logits[:, 0]))
            return np.concatenate(outs)

        np.testing.assert_allclose(decode(True), decode(False),
                                   rtol=2e-4, atol=2e-4)


class TestSlidingWindow:
    """window= bounds the per-slot block range AND the mask — must match
    gqa_attention's sliding_window semantics exactly."""

    @pytest.mark.parametrize("window", [8, 24, 48, 200])
    @pytest.mark.parametrize("block_t", [16, 32])
    def test_matches_xla_sliding_reference(self, window, block_t):
        q, k, v, lengths = make_case(seed=3)
        got = decode_attention(q, k, v, jnp.int32(0), lengths,
                               block_t=block_t, window=window,
                               interpret=True)
        positions = (lengths - 1)[:, None]
        want = gqa_attention(q[:, None], k[0], v[0], positions, lengths,
                             sliding_window=window)[:, 0]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_quantized_sliding(self):
        q, k, v, lengths = make_case(seed=4)
        kq, ksc = quantize_kv(k)
        vq, vsc = quantize_kv(v)
        ksc, vsc = to_minor(ksc), to_minor(vsc)
        got = decode_attention(q, kq, vq, jnp.int32(1), lengths,
                               k_scale=ksc, v_scale=vsc,
                               block_t=16, window=24, interpret=True)
        positions = (lengths - 1)[:, None]
        want = gqa_attention(q[:, None], kq[1], vq[1], positions, lengths,
                             sliding_window=24,
                             k_scale=ksc[1], v_scale=vsc[1])[:, 0]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_window_larger_than_length_is_full_attention(self):
        q, k, v, lengths = make_case(seed=5)
        got = decode_attention(q, k, v, jnp.int32(0), lengths,
                               block_t=16, window=10_000, interpret=True)
        want = reference(q, k[0], v[0], lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
