"""Engine + continuous-batching scheduler tests (tiny model, CPU).

The key property: a continuous batch must be invisible to each request —
greedy tokens from a slot-batched engine equal tokens from a plain
sequential forward loop, regardless of what the other slots are doing.
"""

import asyncio
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.engine import (
    EngineError,
    InferenceEngine,
    SamplingParams,
)
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler, TokenEvent
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import forward, init_cache, init_params, preset


@pytest.fixture(scope="module")
def setup():
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    return cfg, params


def make_engine(cfg, params, slots=2, seq=64, buckets=(16, 32), block=1,
                prefill_chunk=256):
    return InferenceEngine(cfg, params, ByteTokenizer(), max_slots=slots,
                           max_seq_len=seq, prefill_buckets=buckets,
                           cache_dtype=jnp.float32, decode_block=block,
                           prefill_chunk=prefill_chunk)


def reference_greedy(cfg, params, prompt_ids, n_tokens):
    """Plain sequential decode loop — the engine must reproduce this."""
    cache = init_cache(cfg, 1, 64, jnp.float32)
    tokens = jnp.asarray([prompt_ids], jnp.int32)
    logits, cache = forward(params, cfg, tokens, cache)
    out = []
    last = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    out.append(int(last[0]))
    for _ in range(n_tokens - 1):
        logits, cache = forward(params, cfg, last[:, None], cache)
        last = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
        out.append(int(last[0]))
    return out


def run_scheduler_requests(engine, requests):
    """Drive a Scheduler synchronously; returns per-request event lists."""
    sched = Scheduler(engine, debug_invariants=True)
    results = {i: [] for i in range(len(requests))}
    done = {i: threading.Event() for i in range(len(requests))}

    for i, (ids, sampling, max_new) in enumerate(requests):
        def emit(ev, i=i):
            results[i].append(ev)
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(prompt_ids=ids, sampling=sampling,
                                max_new_tokens=max_new, emit=emit,
                                id=f"r{i}"))
    sched.start()
    for ev in done.values():
        assert ev.wait(120), "request did not complete"
    sched.stop()
    return results


class TestEnginePrimitives:
    def test_greedy_matches_reference(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params)
        prompt = list(b"hello world")
        want = reference_greedy(cfg, params, prompt, 8)

        first = engine.prefill_and_insert(0, prompt, SamplingParams())
        got = [first]
        for _ in range(7):
            got.append(int(engine.decode_step()[0]))
        assert got == want

    def test_two_slots_independent(self, setup):
        """Slot 1's stream must not perturb slot 0's greedy tokens."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        pa, pb = list(b"first prompt"), list(b"second, quite different")
        want_a = reference_greedy(cfg, params, pa, 6)
        want_b = reference_greedy(cfg, params, pb, 6)

        got_a = [engine.prefill_and_insert(0, pa, SamplingParams())]
        # Interleave: insert b after a has started decoding.
        got_a.append(int(engine.decode_step()[0]))
        got_b = [engine.prefill_and_insert(1, pb, SamplingParams())]
        for _ in range(4):
            toks = engine.decode_step()
            got_a.append(int(toks[0]))
            got_b.append(int(toks[1]))
        got_b.append(int(engine.decode_step()[1]))
        assert got_a == want_a
        assert got_b == want_b

    def test_prompt_too_long_raises(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, buckets=(16,))
        with pytest.raises(EngineError, match="exceeds"):
            engine.prefill_and_insert(0, list(range(40)), SamplingParams())

    def test_bucket_selection(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, buckets=(16, 32))
        assert engine.bucket_for(3) == 16
        assert engine.bucket_for(16) == 16
        assert engine.bucket_for(17) == 32


class TestScheduler:
    def test_streams_match_reference(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params)
        pa, pb = list(b"alpha beta"), list(b"gamma")
        results = run_scheduler_requests(engine, [
            (pa, SamplingParams(), 6),
            (pb, SamplingParams(), 6),
        ])
        for ids, res in ((pa, results[0]), (pb, results[1])):
            want = reference_greedy(cfg, params, ids, 6)
            want_text = ByteTokenizer().decode(want)
            got_text = "".join(ev.text for ev in res)
            # Events carry only completed text; the concatenation must equal
            # the reference decode (modulo a trailing incomplete codepoint,
            # which flush renders as replacement chars).
            assert got_text.rstrip("�") == want_text.rstrip("�")
            assert res[-1].done
            assert res[-1].finish_reason in ("length", "stop")

    def test_more_requests_than_slots(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, slots=2)
        sched = Scheduler(engine, debug_invariants=True)
        results = {i: [] for i in range(5)}
        done = {i: threading.Event() for i in range(5)}
        for i in range(5):
            def emit(ev, i=i):
                results[i].append(ev)
                if ev.done:
                    done[i].set()
            sched.submit(GenRequest(prompt_ids=list(b"req %d" % i),
                                    sampling=SamplingParams(),
                                    max_new_tokens=4, emit=emit, id=f"r{i}"))
        sched.start()
        for ev in done.values():
            assert ev.wait(120)
        assert all(res[-1].done for res in results.values())
        # All slots free after drain; none leaked.
        assert sched.occupancy == 0
        assert sorted(sched._free) == [0, 1]
        sched.stop()

    def test_block_decode_matches_single_step(self, setup):
        """decode_block=4 must stream the same text as decode_block=1."""
        cfg, params = setup
        prompt = list(b"block decoding test")
        out = {}
        for block in (1, 4):
            engine = make_engine(cfg, params, block=block)
            results = run_scheduler_requests(
                engine, [(prompt, SamplingParams(), 10)])
            out[block] = ("".join(ev.text for ev in results[0]),
                          results[0][-1].finish_reason,
                          results[0][-1].tokens_generated)
        assert out[1] == out[4]

    def test_eos_finishes_stream(self, setup):
        """EOS finishes the stream as "stop" at exactly the position the
        reference sequential decode produces it, and the EOS token itself
        is never emitted as text.

        This test used to bias the lm head's EOS column to a constant
        (lm[:, eos] = 10.0) and assert EOS won within 2 tokens. That was
        not a scheduler race — it was a sign-fragile construction: the
        EOS logit becomes 10·sum(hidden), so whether (and when) EOS is
        the argmax depends on the hidden-state sum, which sits near a
        sign threshold for this prompt/seed. Any numerics drift (BLAS
        kernel order, matmul precision defaults) moved the first-EOS
        position and the `<= 2` bound failed on an unmodified tree.
        Pinning the expectation to the reference decode of the SAME
        biased head asserts the property the test always meant — the
        scheduler stops at the first EOS the model actually produces —
        independent of where that EOS lands."""
        cfg, params = setup
        eos = ByteTokenizer().EOS
        biased = dict(params)
        lm = np.array(params["lm_head"])
        lm[:, eos] = 10.0
        biased["lm_head"] = jnp.asarray(lm)
        budget = 16
        want = reference_greedy(cfg, biased, list(b"hi"), budget)
        assert eos in want, \
            f"lm-head bias no longer yields EOS within {budget} tokens; " \
            f"rebuild the test fixture (got {want})"
        k = want.index(eos) + 1  # tokens_generated counts the EOS
        engine = make_engine(cfg, biased)
        results = run_scheduler_requests(
            engine, [(list(b"hi"), SamplingParams(), budget)])
        last = results[0][-1]
        assert last.finish_reason == "stop"
        assert last.tokens_generated == k
        assert last.tokens_emitted == k - 1  # EOS never streams as text

    def test_capacity_eviction(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, seq=20, buckets=(16,))
        results = run_scheduler_requests(
            engine, [(list(b"0123456789"), SamplingParams(), 500)])
        last = results[0][-1]
        assert last.done and last.finish_reason == "length"
        # 10 prompt + g generated reaches capacity 20 at g=10.
        assert last.tokens_generated == 10

    def test_cancellation_frees_slot(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, slots=1)
        sched = Scheduler(engine, debug_invariants=True)
        events: list[TokenEvent] = []
        done = threading.Event()
        cancelled = threading.Event()

        def emit(ev):
            events.append(ev)
            if len(events) >= 2:
                cancelled.set()
            if ev.done:
                done.set()

        sched.submit(GenRequest(
            prompt_ids=list(b"cancel me"), sampling=SamplingParams(),
            max_new_tokens=10_000, emit=emit,
            cancelled=cancelled.is_set, id="c"))
        sched.start()
        assert done.wait(120)
        assert events[-1].finish_reason == "cancelled"
        # Slot must be reusable afterwards.
        done2 = threading.Event()
        sched.submit(GenRequest(
            prompt_ids=list(b"next"), sampling=SamplingParams(),
            max_new_tokens=3, emit=lambda ev: ev.done and done2.set(),
            id="n"))
        assert done2.wait(120)
        sched.stop()

    def test_engine_crash_fails_open_streams(self, setup):
        """A dying engine loop must emit error events, never hang streams."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.decode_steps_dispatch = lambda: (_ for _ in ()).throw(
            RuntimeError("device wedged"))
        sched = Scheduler(engine)
        events = []
        done = threading.Event()

        def emit(ev):
            events.append(ev)
            if ev.done:
                done.set()

        sched.submit(GenRequest(prompt_ids=list(b"boom"),
                                sampling=SamplingParams(),
                                max_new_tokens=10, emit=emit, id="x"))
        sched.start()
        assert done.wait(60)
        assert events[-1].finish_reason == "error"
        assert "device wedged" in events[-1].error

    def test_cancelled_while_queued_gets_terminal_event(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, slots=1)
        sched = Scheduler(engine)
        ev_a_done = threading.Event()
        ev_b = []
        ev_b_done = threading.Event()
        b_cancelled = threading.Event()
        b_cancelled.set()  # cancelled before it ever reaches a slot

        sched.submit(GenRequest(prompt_ids=list(b"occupier"),
                                sampling=SamplingParams(), max_new_tokens=6,
                                emit=lambda ev: ev.done and ev_a_done.set(),
                                id="a"))
        sched.submit(GenRequest(prompt_ids=list(b"queued"),
                                sampling=SamplingParams(), max_new_tokens=6,
                                emit=lambda ev: (ev_b.append(ev),
                                                 ev.done and ev_b_done.set()),
                                cancelled=b_cancelled.is_set, id="b"))
        sched.start()
        assert ev_a_done.wait(120)
        assert ev_b_done.wait(120)
        assert ev_b[-1].finish_reason == "cancelled"
        sched.stop()

    def test_overlong_prompt_finishes_immediately(self, setup):
        """Prompt with no decode headroom: first token, then length-finish —
        never a decode block whose KV writes would be dropped."""
        cfg, params = setup
        engine = make_engine(cfg, params, seq=20, buckets=(16,), block=8)
        results = run_scheduler_requests(
            engine, [(list(b"0123456789abcdef"), SamplingParams(), 100)])
        last = results[0][-1]
        assert last.done and last.finish_reason == "length"
        assert last.tokens_generated == 1

    def test_ttft_metric_reported(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params)
        results = run_scheduler_requests(
            engine, [(list(b"metrics"), SamplingParams(), 3)])
        ttfts = [ev.ttft_s for ev in results[0] if ev.ttft_s is not None]
        assert ttfts and all(t >= 0 for t in ttfts)


class TestTpuNativeBackend:
    def test_openai_sse_stream(self, setup):
        from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
        from symmetry_tpu.provider.config import ConfigManager

        cfg_mgr = ConfigManager(config={
            "name": "t", "public": False, "serverKey": "00" * 32,
            "modelName": "tiny-test", "apiProvider": "tpu_native",
            "tpu": {"model_preset": "tiny", "dtype": "float32",
                    "max_batch_size": 2, "max_seq_len": 64,
                    "prefill_buckets": [16, 32]},
        })

        async def drive():
            import json as _json

            backend = TpuNativeBackend(cfg_mgr)
            await backend.start()
            assert await backend.healthy()
            chunks = []
            from symmetry_tpu.provider.backends.base import InferenceRequest

            async for ch in backend.stream(InferenceRequest(
                    messages=[{"role": "user", "content": "ping"}],
                    max_tokens=5)):
                chunks.append(ch)
            await backend.stop()
            assert not await backend.healthy()

            assert chunks[0].raw.startswith("data: ")
            first = _json.loads(chunks[0].raw[6:])
            assert first["choices"][0]["delta"] == {"role": "assistant"}
            assert first["model"] == "tiny-test"
            assert chunks[-1].raw == "data: [DONE]"
            assert chunks[-1].done
            fin = _json.loads(chunks[-2].raw[6:])
            assert fin["choices"][0]["finish_reason"] in ("length", "stop")
            return True

        assert asyncio.run(asyncio.wait_for(drive(), 180))


class TestWarmup:
    def test_warmup_then_serve_matches_reference(self, setup):
        """warmup() (pre-traffic decode compile) must not perturb later
        requests: its garbage device writes land beyond every slot's valid
        length and insert resets the lanes it uses."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        engine.warmup()
        prompt = list(b"hello world")
        want = reference_greedy(cfg, params, prompt, 8)
        got = [engine.prefill_and_insert(0, prompt, SamplingParams())]
        for _ in range(7):
            got.append(int(engine.decode_step()[0]))
        assert got == want


class TestSeededReproducibility:
    def test_same_seed_reproduces_full_completion(self, setup):
        """A seeded sampled request must reproduce its ENTIRE completion —
        per-slot RNG streams, not a shared global one — and must be immune
        to other slots' traffic."""
        cfg, params = setup
        engine = make_engine(cfg, params)
        sp = SamplingParams(temperature=0.9, top_p=0.95, seed=123)

        def generate(slot, with_noise):
            toks = [engine.prefill_and_insert(slot, list(b"seeded run"), sp)]
            if with_noise:  # concurrent unseeded stream in the other slot
                engine.prefill_and_insert(1 - slot,
                                          list(b"noise traffic"),
                                          SamplingParams(temperature=1.0))
            for _ in range(8):
                toks.append(int(engine.decode_step()[slot]))
            return toks

        a = generate(0, with_noise=False)
        b = generate(0, with_noise=True)
        c = generate(1, with_noise=False)  # different slot, same seed
        assert a == b == c

    def test_different_seeds_diverge(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params)

        def gen(seed):
            sp = SamplingParams(temperature=1.0, seed=seed)
            toks = [engine.prefill_and_insert(0, list(b"diverge"), sp)]
            for _ in range(8):
                toks.append(int(engine.decode_step()[0]))
            return toks

        assert gen(1) != gen(2)


class TestCoalescedPrefill:
    def test_prefill_many_matches_sequential(self, setup):
        """A coalesced 3-prompt prefill must produce exactly what three
        sequential prefills produce (greedy), then decode correctly."""
        cfg, params = setup
        prompts = [list(b"first"), list(b"the second one"), list(b"third!")]
        wants = [reference_greedy(cfg, params, p, 5) for p in prompts]

        engine = make_engine(cfg, params, slots=4)
        firsts = engine.prefill_and_insert_many(
            [(i, p, SamplingParams()) for i, p in enumerate(prompts)])
        got = [[f] for f in firsts]
        for _ in range(4):
            toks = engine.decode_step()
            for i in range(3):
                got[i].append(int(toks[i]))
        assert got == wants

    def test_prefill_many_mixed_buckets(self, setup):
        """Prompts from different buckets coalesce at the largest bucket."""
        cfg, params = setup
        engine = make_engine(cfg, params, slots=4, buckets=(16, 32))
        short, long = list(b"abc"), list(range(1, 25))
        w_short = reference_greedy(cfg, params, short, 3)
        w_long = reference_greedy(cfg, params, long, 3)
        firsts = engine.prefill_and_insert_many(
            [(0, short, SamplingParams()), (1, long, SamplingParams())])
        got0, got1 = [firsts[0]], [firsts[1]]
        for _ in range(2):
            toks = engine.decode_step()
            got0.append(int(toks[0]))
            got1.append(int(toks[1]))
        assert got0 == w_short
        assert got1 == w_long

    def test_scheduler_coalesces_burst(self, setup):
        """A burst of queued requests admits in grouped prefills and every
        stream still matches the sequential reference."""
        cfg, params = setup
        engine = make_engine(cfg, params, slots=4)
        prompts = [list(b"r0"), list(b"req one"), list(b"request two"),
                   list(b"rrr three")]
        results = run_scheduler_requests(
            engine, [(p, SamplingParams(), 5) for p in prompts])
        for i, p in enumerate(prompts):
            want_text = ByteTokenizer().decode(reference_greedy(
                cfg, params, p, 5))
            got_text = "".join(ev.text for ev in results[i])
            assert got_text.rstrip("�") == want_text.rstrip("�")

    def test_empty_prompt_fails_alone_in_batch(self, setup):
        """An empty prompt in an admission burst must error individually,
        not poison the coalesced group."""
        cfg, params = setup
        engine = make_engine(cfg, params, slots=4)
        good = list(b"fine")
        want = reference_greedy(cfg, params, good, 4)
        results = run_scheduler_requests(engine, [
            (good, SamplingParams(), 4),
            ([], SamplingParams(), 4),
            (good, SamplingParams(), 4),
        ])
        assert results[1][-1].finish_reason == "error"
        for idx in (0, 2):
            got = "".join(ev.text for ev in results[idx])
            want_text = ByteTokenizer().decode(want)
            assert got.rstrip("�") == want_text.rstrip("�")


class TestChunkedPrefill:
    """Chunked prefill (engine.ChunkedPrefill): a long prompt's prefix is
    built chunk-by-chunk so admission never stalls active decode streams —
    and the result must be BIT-IDENTICAL to the monolithic prefill."""

    def test_matches_monolithic_prefill(self, setup):
        cfg, params = setup
        prompt = list(b"a fairly long prompt that spans several chunks!")
        want = reference_greedy(cfg, params, prompt, 6)

        engine = make_engine(cfg, params, buckets=(64,), prefill_chunk=16)
        assert engine.wants_chunked(len(prompt))
        job = engine.start_chunked_prefill(0, prompt, SamplingParams())
        assert job.n_chunks == 3
        first = None
        steps = 0
        while first is None:
            first = engine.advance_chunked_prefill(job)
            steps += 1
        assert steps == job.n_chunks  # one device dispatch per chunk
        got = [first]
        for _ in range(5):
            got.append(int(engine.decode_step()[0]))
        assert got == want

    def test_chunked_alongside_active_decode(self, setup):
        """A chunked prefill must not perturb an active slot's stream."""
        cfg, params = setup
        pa = list(b"short")
        pb = list(b"a fairly long prompt that spans several chunks!")
        want_a = reference_greedy(cfg, params, pa, 10)
        want_b = reference_greedy(cfg, params, pb, 4)

        engine = make_engine(cfg, params, buckets=(16, 64), prefill_chunk=16)
        got_a = [engine.prefill_and_insert(0, pa, SamplingParams())]
        got_a.append(int(engine.decode_step()[0]))
        job = engine.start_chunked_prefill(1, pb, SamplingParams())
        first_b = engine.advance_chunked_prefill(job)
        assert first_b is None
        got_a.append(int(engine.decode_step()[0]))  # decode between chunks
        first_b = engine.advance_chunked_prefill(job)
        got_a.append(int(engine.decode_step()[0]))
        first_b = engine.advance_chunked_prefill(job)
        assert first_b is not None
        got_b = [first_b]
        for _ in range(3):
            toks = engine.decode_step()
            got_a.append(int(toks[0]))
            got_b.append(int(toks[1]))
        for _ in range(3):
            got_a.append(int(engine.decode_step()[0]))
        assert got_a == want_a
        assert got_b == want_b

    def test_scheduler_routes_long_prompts_through_chunks(self, setup):
        cfg, params = setup
        prompt = list(b"a fairly long prompt that spans several chunks!")
        want = reference_greedy(cfg, params, prompt, 6)
        want_text = ByteTokenizer().decode(want)

        engine = make_engine(cfg, params, buckets=(16, 64), prefill_chunk=16)
        results = run_scheduler_requests(
            engine, [(prompt, SamplingParams(), 6)])
        got_text = "".join(ev.text for ev in results[0])
        assert got_text.rstrip("�") == want_text.rstrip("�")
        assert results[0][-1].done


class TestCoalescedPadRows:
    def test_pad_row_overwrite_is_identical(self, setup):
        """A non-full coalesced batch pads by replaying the LAST request —
        with the SAME PRNG keys, so the pad row's overwrite of that slot
        is bit-identical. A fresh-entropy pad would sample a different
        first token and leave decode conditioned on a token the client
        never received (round-3 review finding)."""
        cfg, params = setup
        engine = make_engine(cfg, params, slots=4)
        reqs = [(s, list(b"pad row check %d" % s),
                 SamplingParams(temperature=0.9))  # unseeded + sampled
                for s in range(3)]  # 3 requests -> batch pads to 4
        firsts = engine.prefill_and_insert_many(reqs)
        # the device state each slot will decode from must be exactly the
        # token the caller returned to the stream
        for (slot, _, _), first in zip(reqs, firsts):
            assert int(engine.state.last_token[slot]) == first


class TestPrefillScratchPool:
    def test_pool_is_lru_bounded(self, setup):
        """The persistent prefill scratch pool must stay bounded: pinning
        every (batch, bucket) grid shape forever would cost more steady
        HBM than the per-dispatch churn it replaces (round-4 review)."""
        cfg, params = setup
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=8, max_seq_len=64,
            prefill_buckets=(16, 32), cache_dtype=jnp.float32,
            prefill_token_budget=64)
        engine.warmup()  # touches the whole grid
        lanes = sum(b * bk for (b, bk) in engine._prefill_scratch)
        assert lanes <= 3 * engine.prefill_token_budget, lanes

        # a shape in active reuse stays pooled (no realloc churn)
        prompt = list(b"twelve bytes")
        engine.prefill_and_insert_many(
            [(s, prompt, SamplingParams()) for s in range(4)])
        key = (4, 16)
        pooled = engine._prefill_scratch.get(key)
        assert pooled is not None
        engine.prefill_and_insert_many(
            [(s, prompt, SamplingParams()) for s in range(4, 8)])
        assert engine._prefill_scratch.get(key) is not None

    def test_scratch_reuse_is_correct(self, setup):
        """Back-to-back same-shape prefills through the donated scratch
        must match fresh sequential references (dirty-buffer reuse)."""
        cfg, params = setup
        engine = make_engine(cfg, params, slots=2)
        p1, p2 = list(b"hello scratch"), list(b"other prompt!")
        want1 = reference_greedy(cfg, params, p1, 3)
        want2 = reference_greedy(cfg, params, p2, 3)
        got1 = [engine.prefill_and_insert(0, p1, SamplingParams())]
        got2 = [engine.prefill_and_insert(1, p2, SamplingParams())]
        for _ in range(2):
            toks = engine.decode_step()
            got1.append(int(toks[0]))
            got2.append(int(toks[1]))
        assert got1 == want1 and got2 == want2
