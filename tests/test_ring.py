"""Ring attention over a context-sharded CPU mesh vs single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.parallel import MeshSpec, build_mesh
from symmetry_tpu.parallel.ring import ring_attention
from tests.test_ops import naive_attention


@pytest.fixture(scope="module")
def ring_mesh():
    return build_mesh(MeshSpec(context=4))


class TestRingAttention:
    @pytest.mark.parametrize("nq,nkv", [(4, 4), (4, 2)])
    def test_matches_naive(self, ring_mesh, nq, nkv):
        rng = np.random.default_rng(0)
        B, S, D = 2, 64, 16
        q = rng.normal(size=(B, S, nq, D)).astype(np.float32)
        k = rng.normal(size=(B, S, nkv, D)).astype(np.float32)
        v = rng.normal(size=(B, S, nkv, D)).astype(np.float32)
        seq_lens = np.array([64, 50], np.int32)

        got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             jnp.asarray(seq_lens), ring_mesh)
        q_pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S))
        want = naive_attention(q, k, v, q_pos, seq_lens)
        got = np.asarray(got)
        for b in range(B):
            n = seq_lens[b]
            np.testing.assert_allclose(got[b, :n], want[b, :n],
                                       rtol=2e-4, atol=2e-4)
        assert not np.isnan(got).any()

    def test_jits_with_sharded_inputs(self, ring_mesh):
        """Under jit with context-sharded inputs the ring compiles and the
        output keeps the sequence sharding."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        B, S, H, D = 1, 32, 2, 8
        q = jax.device_put(
            jnp.ones((B, S, H, D)),
            NamedSharding(ring_mesh, P(None, "context", None, None)))
        seq_lens = jnp.asarray([S], jnp.int32)

        out = jax.jit(
            lambda q: ring_attention(q, q, q, seq_lens, ring_mesh))(q)
        assert out.shape == (B, S, H, D)
        assert out.sharding.spec == P(None, "context", None, None)

    def test_rejects_indivisible(self, ring_mesh):
        q = jnp.ones((1, 30, 2, 8))
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, q, q, jnp.asarray([30]), ring_mesh)


class TestRingInModel:
    def test_ring_prefill_matches_default(self, ring_mesh):
        """Full trunk with ring attention == default masked path."""
        from symmetry_tpu.models import init_cache, init_params, preset
        from symmetry_tpu.models.llama import forward_hidden

        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 512, (2, 64)), jnp.int32)
        seq_lens = jnp.asarray([64, 40], jnp.int32)

        h_ref, _ = forward_hidden(
            params, cfg, tokens, init_cache(cfg, 2, 64, jnp.float32),
            seq_lens=seq_lens)
        h_ring, cache_ring = forward_hidden(
            params, cfg, tokens, init_cache(cfg, 2, 64, jnp.float32),
            seq_lens=seq_lens, prefill_flash=True, ring_mesh=ring_mesh)

        np.testing.assert_allclose(np.asarray(h_ring[0]), np.asarray(h_ref[0]),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(h_ring[1, :40]),
                                   np.asarray(h_ref[1, :40]),
                                   rtol=2e-4, atol=2e-4)
        assert int(cache_ring.lengths[1]) == 40

    def test_ring_without_prefill_contract_rejected(self, ring_mesh):
        from symmetry_tpu.models import init_cache, init_params, preset
        from symmetry_tpu.models.llama import forward_hidden

        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        tokens = jnp.ones((1, 64), jnp.int32)
        with pytest.raises(ValueError, match="prefill_flash"):
            forward_hidden(params, cfg, tokens,
                           init_cache(cfg, 1, 64, jnp.float32),
                           ring_mesh=ring_mesh)
