"""Flash prefill kernel vs the naive attention reference (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.models import forward, init_cache, init_params, preset
from symmetry_tpu.models.llama import forward_hidden
from symmetry_tpu.ops.flash import flash_prefill
from tests.test_ops import naive_attention


class TestFlashKernel:
    @pytest.mark.parametrize("nq,nkv,S", [(4, 4, 32), (4, 2, 64), (8, 1, 32)])
    def test_matches_naive_full_length(self, nq, nkv, S):
        rng = np.random.default_rng(0)
        B, D = 2, 32
        q = rng.normal(size=(B, S, nq, D)).astype(np.float32)
        k = rng.normal(size=(B, S, nkv, D)).astype(np.float32)
        v = rng.normal(size=(B, S, nkv, D)).astype(np.float32)
        seq_lens = np.array([S, S], np.int32)
        got = flash_prefill(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            jnp.asarray(seq_lens), block_q=16, block_k=16,
                            interpret=True)
        q_pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S))
        want = naive_attention(q, k, v, q_pos, seq_lens)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)

    def test_ragged_lengths_masked(self):
        """Valid rows must ignore K/V past each sample's seq_len."""
        rng = np.random.default_rng(1)
        B, S, H, D = 2, 32, 2, 16
        q = rng.normal(size=(B, S, H, D)).astype(np.float32)
        k = rng.normal(size=(B, S, H, D)).astype(np.float32)
        v = rng.normal(size=(B, S, H, D)).astype(np.float32)
        seq_lens = np.array([20, 7], np.int32)
        got = np.asarray(flash_prefill(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(seq_lens), block_q=16, block_k=16, interpret=True))
        q_pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S))
        want = naive_attention(q, k, v, q_pos, seq_lens)
        for b in range(B):
            n = seq_lens[b]
            np.testing.assert_allclose(got[b, :n], want[b, :n],
                                       rtol=2e-4, atol=2e-4)
        assert not np.isnan(got).any(), "padded rows must not be NaN"

    def test_rejects_unaligned(self):
        q = jnp.zeros((1, 20, 2, 16))
        with pytest.raises(ValueError, match="not a multiple"):
            flash_prefill(q, q, q, jnp.asarray([20]), block_q=16, block_k=16,
                          interpret=True)


class TestFlashInModel:
    def test_prefill_flash_matches_masked_path(self):
        """forward_hidden(prefill_flash=True) == default path on fresh cache."""
        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(0, 512, (2, 32)), jnp.int32)
        seq_lens = jnp.asarray([32, 11], jnp.int32)

        h_ref, cache_ref = forward_hidden(
            params, cfg, tokens, init_cache(cfg, 2, 32, jnp.float32),
            seq_lens=seq_lens)
        h_flash, cache_flash = forward_hidden(
            params, cfg, tokens, init_cache(cfg, 2, 32, jnp.float32),
            seq_lens=seq_lens, prefill_flash=True)

        # Valid positions agree; caches identical (flash changes attention
        # reads, not KV writes).
        np.testing.assert_allclose(np.asarray(h_flash[0]), np.asarray(h_ref[0]),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(h_flash[1, :11]),
                                   np.asarray(h_ref[1, :11]),
                                   rtol=2e-4, atol=2e-4)
        # KV writes are the same math in both graphs (XLA fusion may differ
        # at float-rounding level; deeper layers also inherit divergence
        # through earlier attention outputs).
        np.testing.assert_allclose(np.asarray(cache_flash.k),
                                   np.asarray(cache_ref.k),
                                   rtol=2e-4, atol=2e-4)

    def test_flash_prefill_then_decode_consistent(self):
        """Engine-style: flash prefill, then decode steps match full forward."""
        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        seq = np.random.default_rng(3).integers(0, 512, 20).astype(np.int32)

        cache_full = init_cache(cfg, 1, 32, jnp.float32)
        want, _ = forward(params, cfg, jnp.asarray(seq[None]), cache_full)

        cache = init_cache(cfg, 1, 32, jnp.float32)
        _, cache = forward_hidden(params, cfg, jnp.asarray(seq[None, :16]),
                                  cache, prefill_flash=True)
        logits = None
        for i in range(16, 20):
            logits, cache = forward(params, cfg, jnp.asarray(seq[None, i:i+1]),
                                    cache)
        np.testing.assert_allclose(np.asarray(logits[0, 0]),
                                   np.asarray(want[0, -1]),
                                   rtol=1e-4, atol=1e-4)


class TestSlidingWindowFlash:
    @pytest.mark.parametrize("window", [8, 17, 64])
    def test_window_matches_naive(self, window):
        rng = np.random.default_rng(3)
        B, S, nq, nkv, D = 2, 64, 4, 2, 32
        q = rng.normal(size=(B, S, nq, D)).astype(np.float32)
        k = rng.normal(size=(B, S, nkv, D)).astype(np.float32)
        v = rng.normal(size=(B, S, nkv, D)).astype(np.float32)
        seq_lens = np.array([64, 41], np.int32)
        got = flash_prefill(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            jnp.asarray(seq_lens), block_q=16, block_k=16,
                            window=window, interpret=True)
        q_pos = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S))
        want = naive_attention(q, k, v, q_pos, seq_lens, window=window)
        for b in range(B):
            n = seq_lens[b]
            np.testing.assert_allclose(np.asarray(got)[b, :n], want[b, :n],
                                       rtol=2e-4, atol=2e-4)

    def test_sliding_model_flash_matches_masked(self):
        """A mistral-v0.1-style config now routes prefill through the
        window-bounded flash kernel; result must equal the masked path."""
        import dataclasses

        from symmetry_tpu.models import init_cache, init_params, preset

        cfg = dataclasses.replace(preset("tiny"), sliding_window=12)
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        rng = np.random.default_rng(4)
        tokens = jnp.asarray(rng.integers(0, 512, (2, 32)), jnp.int32)
        seq_lens = jnp.asarray([32, 20], jnp.int32)

        h_masked, _ = forward_hidden(
            params, cfg, tokens, init_cache(cfg, 2, 32, jnp.float32),
            seq_lens=seq_lens, prefill_flash=False)
        h_flash, _ = forward_hidden(
            params, cfg, tokens, init_cache(cfg, 2, 32, jnp.float32),
            seq_lens=seq_lens, prefill_flash=True)
        for b, n in enumerate([32, 20]):
            np.testing.assert_allclose(
                np.asarray(h_flash)[b, :n], np.asarray(h_masked)[b, :n],
                rtol=2e-4, atol=2e-4)
