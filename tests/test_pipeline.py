"""Pipeline parallelism (parallel/pipeline.py) vs the plain forward path.

4 stages over the 8-device virtual CPU mesh; the staged, microbatched
schedule must be invisible: same hidden states, same KV cache, and decode
must continue seamlessly from a pipeline-prefilled cache.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.models import init_cache, init_params
from symmetry_tpu.models.llama import ModelConfig, forward_hidden
from symmetry_tpu.parallel import MeshSpec, build_mesh
from symmetry_tpu.parallel.pipeline import (
    PIPELINE_RULES,
    pipeline_forward_hidden,
)

pytestmark = pytest.mark.slow  # multi-process / heavy-compile; run with -m ""

CFG = ModelConfig(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                  num_kv_heads=2, intermediate_size=96, rope_theta=10000.0,
                  max_position=128)


@pytest.fixture(scope="module")
def pp_mesh():
    return build_mesh(MeshSpec(stage=4))


@pytest.fixture(scope="module")
def setup():
    params = init_params(CFG, jax.random.key(0), jnp.float32)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 256, (4, 16)), jnp.int32)
    return params, tokens


class TestPipelineForward:
    @pytest.mark.parametrize("n_micro", [1, 2, 4])
    def test_matches_plain_forward(self, pp_mesh, setup, n_micro):
        params, tokens = setup
        seq_lens = jnp.asarray([16, 9, 16, 4], jnp.int32)

        want_h, want_cache = forward_hidden(
            params, CFG, tokens, init_cache(CFG, 4, 32, jnp.float32),
            seq_lens=seq_lens)
        got_h, got_cache = pipeline_forward_hidden(
            params, CFG, tokens, init_cache(CFG, 4, 32, jnp.float32),
            pp_mesh, seq_lens=seq_lens, n_microbatches=n_micro)

        np.testing.assert_allclose(np.asarray(got_h), np.asarray(want_h),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_array_equal(np.asarray(got_cache.lengths),
                                      np.asarray(want_cache.lengths))
        # cache contents match where valid (per slot, per true length)
        for b, n in enumerate([16, 9, 16, 4]):
            np.testing.assert_allclose(
                np.asarray(got_cache.k)[:, b, :n],
                np.asarray(want_cache.k)[:, b, :n], rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(
                np.asarray(got_cache.v)[:, b, :n],
                np.asarray(want_cache.v)[:, b, :n], rtol=2e-4, atol=2e-4)

    def test_decode_continues_from_pipeline_prefill(self, pp_mesh, setup):
        """Prefill through the pipeline, then decode steps through the
        pipeline: token-for-token equal to the plain path."""
        params, tokens = setup

        def greedy(h, params):
            from symmetry_tpu.models.llama import logits_from_hidden

            logits = logits_from_hidden(params, CFG, h[:, -1:])
            return jnp.argmax(logits[:, 0], -1).astype(jnp.int32)

        # plain reference
        cache_ref = init_cache(CFG, 4, 32, jnp.float32)
        h, cache_ref = forward_hidden(params, CFG, tokens, cache_ref)
        ref_toks = [np.asarray(greedy(h, params))]
        last = greedy(h, params)
        for _ in range(3):
            h, cache_ref = forward_hidden(params, CFG, last[:, None],
                                          cache_ref)
            last = greedy(h, params)
            ref_toks.append(np.asarray(last))

        # pipelined
        cache = init_cache(CFG, 4, 32, jnp.float32)
        h, cache = pipeline_forward_hidden(params, CFG, tokens, cache,
                                           pp_mesh, n_microbatches=2)
        pp_toks = [np.asarray(greedy(h, params))]
        last = greedy(h, params)
        for _ in range(3):
            h, cache = pipeline_forward_hidden(params, CFG, last[:, None],
                                               cache, pp_mesh,
                                               n_microbatches=2)
            last = greedy(h, params)
            pp_toks.append(np.asarray(last))

        np.testing.assert_array_equal(np.stack(pp_toks), np.stack(ref_toks))

    def test_sharded_params_and_cache(self, pp_mesh, setup):
        """With params/cache actually placed stage-sharded, the pipeline
        compiles under jit and produces the same result."""
        from symmetry_tpu.models.llama import param_logical_axes
        from symmetry_tpu.parallel import shardings_for

        params, tokens = setup
        sharded = jax.device_put(
            params, shardings_for(param_logical_axes(CFG), pp_mesh,
                                  PIPELINE_RULES))
        want_h, _ = forward_hidden(
            params, CFG, tokens, init_cache(CFG, 4, 32, jnp.float32))
        got_h, _ = pipeline_forward_hidden(
            sharded, CFG, tokens, init_cache(CFG, 4, 32, jnp.float32),
            pp_mesh, n_microbatches=2)
        np.testing.assert_allclose(np.asarray(got_h), np.asarray(want_h),
                                   rtol=2e-4, atol=2e-4)

    def test_rejects_bad_divisibility(self, pp_mesh, setup):
        params, tokens = setup
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_forward_hidden(params, CFG, tokens,
                                    init_cache(CFG, 4, 32, jnp.float32),
                                    pp_mesh, n_microbatches=3)
        bad_cfg = dataclasses.replace(CFG, num_layers=6)
        with pytest.raises(ValueError, match="stages"):
            pipeline_forward_hidden(params, bad_cfg, tokens,
                                    init_cache(CFG, 4, 32, jnp.float32),
                                    pp_mesh, n_microbatches=2)

    def test_flash_prefill_pipeline(self, pp_mesh, setup):
        """prefill_flash routes each stage's attention through the flash
        kernel (interpret on CPU) — same results as the masked path."""
        params, tokens = setup
        want_h, _ = forward_hidden(
            params, CFG, tokens, init_cache(CFG, 4, 32, jnp.float32))
        got_h, _ = pipeline_forward_hidden(
            params, CFG, tokens, init_cache(CFG, 4, 32, jnp.float32),
            pp_mesh, n_microbatches=2, prefill_flash=True)
        np.testing.assert_allclose(np.asarray(got_h), np.asarray(want_h),
                                   rtol=2e-4, atol=2e-4)

    def test_rejects_non_stage_sharding(self, setup):
        params, tokens = setup
        mesh = build_mesh(MeshSpec(stage=2, model=2))
        with pytest.raises(ValueError, match="stage-only"):
            pipeline_forward_hidden(params, CFG, tokens,
                                    init_cache(CFG, 4, 32, jnp.float32),
                                    mesh, n_microbatches=2)

    def test_config_depth_mismatch_raises(self, setup):
        params, tokens = setup
        bad = dataclasses.replace(CFG, num_layers=8)
        with pytest.raises(ValueError, match="stacked layers"):
            forward_hidden(params, bad, tokens,
                           init_cache(bad, 4, 32, jnp.float32))

    def test_quantized_cache_pipeline(self, pp_mesh, setup):
        params, tokens = setup
        want_h, _ = forward_hidden(
            params, CFG, tokens, init_cache(CFG, 4, 32, jnp.float32,
                                            quantized=True))
        got_h, got_cache = pipeline_forward_hidden(
            params, CFG, tokens,
            init_cache(CFG, 4, 32, jnp.float32, quantized=True),
            pp_mesh, n_microbatches=2)
        np.testing.assert_allclose(np.asarray(got_h), np.asarray(want_h),
                                   rtol=2e-4, atol=2e-4)
        assert got_cache.k.dtype == jnp.int8


class TestPipelineEngine:
    def test_engine_pipeline_greedy_matches_plain(self, pp_mesh, setup):
        """The full serving engine in pipeline mode (stage-sharded params
        and cache, staged prefill + decode) reproduces the plain engine's
        greedy tokens."""
        from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
        from symmetry_tpu.engine.tokenizer import ByteTokenizer
        from symmetry_tpu.models.llama import param_logical_axes
        from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for

        params, _ = setup
        mesh2 = build_mesh(MeshSpec(stage=2))

        def run(mesh, p, n_micro):
            eng = InferenceEngine(
                CFG, p, ByteTokenizer(), mesh=mesh, max_slots=2,
                max_seq_len=64, prefill_buckets=(16,),
                cache_dtype=jnp.float32, pipeline_microbatches=n_micro)
            toks = [eng.prefill_and_insert(0, list(b"pipeline serve"),
                                           SamplingParams())]
            eng.prefill_and_insert(1, list(b"other"), SamplingParams())
            for _ in range(6):
                toks.append(int(eng.decode_step()[0]))
            return toks

        sharded = jax.device_put(
            params, shardings_for(param_logical_axes(CFG), mesh2,
                                  PIPELINE_RULES))
        assert run(mesh2, sharded, 2) == run(None, params, 1)
