"""Batched host pipe protocol: one frame per block, both shapes parse.

Two halves of the pipe, each tested against the wire contract in
engine/host.py's docstring:

  - EngineHost._emit_batch (producer): a scheduler block flush becomes
    ONE stdout write — the `events` frame — with per-event delta
    bookkeeping (tokens_new) and done/finish_reason fidelity; a lone
    event keeps the legacy `event` frame. Asserted via the emit-path
    counters (pipe_writes), the O(1)-writes-per-block acceptance gate.

  - TpuNativeBackend._read_events (consumer): a mixed stream of batched
    `events` frames and legacy single-event frames fans out to the right
    per-request queues, preserving per-stream ordering; abandoning a
    stream mid-block cancels it host-side.
"""

import asyncio
import json
from types import SimpleNamespace

from symmetry_tpu.engine.engine import SamplingParams
from symmetry_tpu.engine.host import EngineHost
from symmetry_tpu.engine.scheduler import GenRequest, TokenEvent
from symmetry_tpu.provider.backends.base import InferenceRequest
from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
from symmetry_tpu.provider.config import ConfigManager


def make_req(rid: str) -> GenRequest:
    return GenRequest(prompt_ids=[1], sampling=SamplingParams(),
                      max_new_tokens=16, emit=lambda ev: None, id=rid)


class TestHostEmitBatch:
    def test_block_batch_is_one_pipe_write(self, capsys):
        host = EngineHost(config=None)  # config untouched before start()
        host._reported = {"r1": 0, "r2": 0, "r3": 0}
        batch = [
            (make_req("r1"), TokenEvent(text="ab", token_id=98,
                                        tokens_generated=9,
                                        tokens_emitted=9)),
            # r2 stops on EOS: generated counts it, emitted does not —
            # tokens_new must ride the emitted count.
            (make_req("r2"), TokenEvent(text="c", token_id=99,
                                        tokens_generated=4,
                                        tokens_emitted=3, done=True,
                                        finish_reason="stop")),
            (make_req("r3"), TokenEvent(text="", token_id=None,
                                        tokens_generated=2,
                                        tokens_emitted=1, done=True,
                                        finish_reason="error",
                                        error="boom")),
        ]
        host._emit_batch(batch)
        assert host.emit_stats["pipe_writes"] == 1  # O(1) per block
        assert host.emit_stats["pipe_event_writes"] == 1
        assert host.emit_stats["pipe_events"] == 3
        assert host.emit_stats["pipe_batched_frames"] == 1

        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 1
        frame = json.loads(lines[0])
        assert frame["op"] == "events"
        e1, e2, e3 = frame["events"]
        assert e1 == {"id": "r1", "text": "ab", "tokens": 9,
                      "tokens_new": 9}
        assert e2["done"] and e2["finish_reason"] == "stop"
        assert e2["tokens"] == 4       # generated keeps the EOS…
        assert e2["tokens_new"] == 3   # …streamed-token deltas do not
        assert e3["finish_reason"] == "error" and e3["error"] == "boom"
        # done events retire their delta bookkeeping
        assert host._reported == {"r1": 9}

    def test_single_event_keeps_legacy_frame(self, capsys):
        host = EngineHost(config=None)
        host._reported = {"r1": 3}
        host._emit_batch([(make_req("r1"), TokenEvent(
            text="d", token_id=100, tokens_generated=5,
            tokens_emitted=5))])
        frame = json.loads(capsys.readouterr().out)
        assert frame["op"] == "event"  # wire-compatible with old readers
        assert frame["tokens_new"] == 2  # cumulative 5 - reported 3
        assert host.emit_stats["pipe_writes"] == 1
        assert host.emit_stats["pipe_batched_frames"] == 0


def backend_fixture():
    cfg = ConfigManager(config={
        "name": "t", "public": False, "serverKey": "00" * 32,
        "modelName": "tiny-test", "apiProvider": "tpu_native",
        "tpu": {"model_preset": "tiny", "dtype": "float32",
                "max_batch_size": 2, "max_seq_len": 64,
                "prefill_buckets": [16, 32]},
    })
    backend = TpuNativeBackend(cfg)

    class FakeStdin:
        def __init__(self):
            self.lines: list[bytes] = []

        def write(self, data: bytes) -> None:
            self.lines.append(data)

        async def drain(self) -> None:
            pass

    reader = asyncio.StreamReader()
    stdin = FakeStdin()
    backend._proc = SimpleNamespace(stdout=reader, stdin=stdin,
                                    returncode=None, pid=1)
    backend._started = True
    return backend, reader, stdin


def feed(reader: asyncio.StreamReader, obj: dict) -> None:
    reader.feed_data((json.dumps(obj) + "\n").encode())


async def wait_registered(backend, *ids, timeout=5.0):
    async def poll():
        while not all(i in backend._queues for i in ids):
            await asyncio.sleep(0.001)
    await asyncio.wait_for(poll(), timeout)


REQ = InferenceRequest(messages=[{"role": "user", "content": "hi"}])


class TestTpuNativeMixedFrames:
    def test_mixed_batched_and_legacy_frames_round_trip(self):
        async def main():
            backend, reader, _stdin = backend_fixture()
            reader_task = asyncio.ensure_future(backend._read_events())

            async def collect(req_id):
                out = []
                async for ch in backend._stream_host(REQ, req_id, 0, 16):
                    out.append(ch)
                return out

            t1 = asyncio.ensure_future(collect("r1"))
            t2 = asyncio.ensure_future(collect("r2"))
            await wait_registered(backend, "r1", "r2")

            # legacy single-event frame …
            feed(reader, {"op": "event", "id": "r1", "text": "Hel",
                          "tokens": 3, "tokens_new": 3})
            # … a batched frame interleaving both streams …
            feed(reader, {"op": "events", "events": [
                {"id": "r1", "text": "lo", "tokens": 5, "tokens_new": 2},
                {"id": "r2", "text": "wor", "tokens": 3, "tokens_new": 3},
            ]})
            # … and a batched frame carrying both finishes.
            feed(reader, {"op": "events", "events": [
                {"id": "r1", "text": "", "done": True,
                 "finish_reason": "stop", "tokens": 5, "tokens_new": 0},
                {"id": "r2", "text": "ld", "done": True,
                 "finish_reason": "length", "tokens": 5, "tokens_new": 2},
            ]})
            c1, c2 = await asyncio.gather(t1, t2)

            # Per-stream ordering and content survive the mixed shapes.
            assert "".join(ch.text for ch in c1) == "Hello"
            assert "".join(ch.text for ch in c2) == "world"
            # done/finish_reason fidelity: finish chunk then [DONE]
            fin1 = json.loads(c1[-2].raw[len("data: "):])
            assert fin1["choices"][0]["finish_reason"] == "stop"
            fin2 = json.loads(c2[-2].raw[len("data: "):])
            assert fin2["choices"][0]["finish_reason"] == "length"
            assert c1[-1].done and c2[-1].done
            assert sum(ch.tokens or 0 for ch in c2) == 5

            assert backend.relay_stats == {"host_frames": 3,
                                           "host_events": 5,
                                           "host_batched_frames": 2}
            reader_task.cancel()

        asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(main(), 30))

    def test_abandoned_stream_cancels_mid_block(self):
        async def main():
            backend, reader, stdin = backend_fixture()
            reader_task = asyncio.ensure_future(backend._read_events())

            agen = backend._stream_host(REQ, "r3", 0, 16)
            got = []
            # advance until the first content chunk, then abandon
            consume = asyncio.ensure_future(agen.__anext__())
            await wait_registered(backend, "r3")
            got.append(await consume)  # role chunk
            feed(reader, {"op": "events", "events": [
                {"id": "r3", "text": "par", "tokens": 3, "tokens_new": 3}]})
            got.append(await agen.__anext__())
            assert got[-1].text == "par"
            await agen.aclose()  # client walks away mid-block

            sent = [json.loads(line) for line in
                    b"".join(stdin.lines).decode().strip().splitlines()]
            assert {"op": "cancel", "id": "r3"} in sent
            assert "r3" not in backend._queues
            reader_task.cancel()

        asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(main(), 30))

    def test_malformed_and_unknown_events_ignored(self):
        async def main():
            backend, reader, _stdin = backend_fixture()
            reader_task = asyncio.ensure_future(backend._read_events())

            async def collect(req_id):
                out = []
                async for ch in backend._stream_host(REQ, req_id, 0, 16):
                    out.append(ch)
                return out

            t = asyncio.ensure_future(collect("r4"))
            await wait_registered(backend, "r4")
            feed(reader, {"op": "events", "events": "garbage"})
            feed(reader, {"op": "events", "events": [
                "junk",
                {"id": "nobody-home", "text": "zzz"},
                {"id": "r4", "text": "ok", "tokens": 2, "tokens_new": 2},
            ]})
            feed(reader, {"op": "event", "id": "r4", "text": "", "done": True,
                          "finish_reason": "stop", "tokens": 2,
                          "tokens_new": 0})
            chunks = await t
            assert "".join(ch.text for ch in chunks) == "ok"
            reader_task.cancel()

        asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(main(), 30))


class TestHostProfileOp:
    """HostOp.PROFILE round-trip (PR-15): the capture runs on its own
    thread (the serve loop must keep flowing for its whole window) and
    the reply carries the artifact path — or a structured error when a
    capture is already holding the single-flight window."""

    def _wait_reply(self, capsys, timeout=120.0):
        # Generous: the process's FIRST jax.profiler capture pays a
        # cold-init cost of tens of seconds on a loaded box.
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            out = capsys.readouterr().out
            if out.strip():
                return [json.loads(line)
                        for line in out.strip().splitlines()]
            _time.sleep(0.05)
        raise AssertionError("no profile reply on the pipe")

    def test_profile_op_replies_with_artifact_path(self, capsys,
                                                   tmp_path):
        import os

        host = EngineHost(config=None)
        host._handle_profile({"op": "profile", "duration_s": 0.05,
                              "dir": str(tmp_path)})
        (reply,) = self._wait_reply(capsys)
        assert reply["op"] == "profile"
        assert reply.get("error") is None, reply
        assert os.path.isdir(reply["path"])
        assert str(tmp_path) in reply["path"]

    def test_concurrent_capture_refused_as_error_reply(self, capsys,
                                                       tmp_path):
        import threading
        import time as _time

        from symmetry_tpu.utils.devprof import capture_device_profile

        host = EngineHost(config=None)
        hold = threading.Thread(target=capture_device_profile,
                                args=(str(tmp_path),),
                                kwargs={"duration_s": 0.8})
        hold.start()
        _time.sleep(0.2)
        host._handle_profile({"op": "profile", "duration_s": 0.05,
                              "dir": str(tmp_path)})
        try:
            (reply,) = self._wait_reply(capsys)
        finally:
            hold.join()
        assert reply["op"] == "profile"
        assert "already running" in (reply.get("error") or "")
