"""Resumable streams: crash-surviving generation (PR 14).

Three layers, all JAX-CPU / fake-host local (no crypto, no TPU):

  - ENGINE: a resumed request — prompt + already-emitted tokens, RNG
    lane fast-forwarded by `rng_skip` — continues token-identical to the
    uninterrupted run, for greedy AND seeded sampling (the RNG-chain
    restore is the part greedy can't exercise).
  - SCHEDULER: the resume admission path — resume_offset accounting
    (sym_resume_* counters), the radix-cache hit on the prompt+emitted
    prefix (tokens_reused > 0: a resume is a cheap seeded re-prefill,
    not a full regeneration), and the first-event resume riders.
  - HOST/BACKEND: the wire — EngineHost._submit's resume parsing, and
    TpuNativeBackend against the protocol-faithful fake host: crash
    mid-stream stamps the journal's emitted count into the restarting
    shed, a resume submit streams only the continuation, and the
    relay's offset dedup drops deliberately-overlapping events.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import sys
import threading

import jax
import jax.numpy as jnp
import pytest

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.host import EngineHost
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import init_params, preset
from symmetry_tpu.provider.backends.base import (
    BackendRestartingError,
    InferenceRequest,
    ResumeJournal,
)
from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.utils.faults import FAULTS

FAKE_HOST = os.path.join(os.path.dirname(__file__), "fake_host.py")


@pytest.fixture(autouse=True)
def clean_global_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


@pytest.fixture(scope="module")
def setup():
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    return cfg, params


def make_engine(cfg, params, slots=4, cache_mb=16, chunk=8,
                buckets=(16, 32, 64), block=8):
    return InferenceEngine(
        cfg, params, ByteTokenizer(vocab_size=cfg.vocab_size),
        max_slots=slots, max_seq_len=128,
        prefill_buckets=buckets, cache_dtype=jnp.float32,
        prefill_chunk=chunk, prefix_cache_bytes=cache_mb * 2**20,
        prefix_block_tokens=block)


def engine_generate(engine, slot, prompt_ids, sampling, n):
    """n sampled token ids for one request, engine-level (no scheduler):
    prefill then single-slot decode blocks. EOS is NOT cut — identity is
    judged on the raw sampled chain, which a resume must reproduce."""
    first = engine.prefill_and_insert(slot, prompt_ids, sampling)
    out = [first]
    while len(out) < n:
        toks = engine.decode_steps()  # [K, B]
        for k in range(toks.shape[0]):
            out.append(int(toks[k, slot]))
            if len(out) >= n:
                break
    engine.release_slot(slot)
    return out


PROMPT = list(b"resumable streams survive host crashes")  # 38 ids


class TestEngineResumeIdentity:
    """The tentpole contract at the engine: continuation == tail of the
    uninterrupted run. The resumed request conditions on prompt + the
    ACTUAL emitted ids (the host derives them from the client's text;
    here the id-level contract is pinned directly) with the RNG lane
    fast-forwarded by rng_skip."""

    N, K = 12, 5  # full length, interruption point

    def _roundtrip(self, setup, sampling):
        cfg, params = setup
        engine = make_engine(cfg, params)
        full = engine_generate(engine, 0, PROMPT, sampling, self.N)
        resumed_sampling = dataclasses.replace(sampling, rng_skip=self.K)
        cont = engine_generate(
            engine, 1, PROMPT + full[:self.K], resumed_sampling,
            self.N - self.K)
        assert cont == full[self.K:], (full, cont)

    def test_greedy_resume_token_identity(self, setup):
        self._roundtrip(setup, SamplingParams())

    def test_seeded_resume_token_identity(self, setup):
        # Temperature high enough that a wrong RNG position would
        # scramble the continuation immediately.
        self._roundtrip(setup, SamplingParams(temperature=0.9, top_p=0.95,
                                              seed=1234))

    def test_seeded_resume_wrong_skip_diverges(self, setup):
        """Negative control: the RNG fast-forward is load-bearing — an
        off-by-one lane position changes the sampled continuation."""
        cfg, params = setup
        engine = make_engine(cfg, params, cache_mb=0)
        sampling = SamplingParams(temperature=0.9, top_p=0.95, seed=1234)
        full = engine_generate(engine, 0, PROMPT, sampling, self.N)
        wrong = dataclasses.replace(sampling, rng_skip=self.K - 1)
        cont = engine_generate(
            engine, 1, PROMPT + full[:self.K], wrong, self.N - self.K)
        assert cont != full[self.K:]

    def test_rng_skip_zero_is_identity(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, cache_mb=0)
        s0 = SamplingParams(temperature=0.7, seed=9)
        s_skip0 = dataclasses.replace(s0, rng_skip=0)
        a = engine_generate(engine, 0, PROMPT, s0, 6)
        b = engine_generate(engine, 1, PROMPT, s_skip0, 6)
        assert a == b

    def test_resume_survives_engine_restart(self, setup):
        """The cross-host case: the continuation runs on a FRESH engine
        (empty radix tree, fresh slot state) — exactly what a respawned
        or different provider sees — and is still token-identical."""
        cfg, params = setup
        engine1 = make_engine(cfg, params)
        sampling = SamplingParams(temperature=0.8, seed=77)
        full = engine_generate(engine1, 0, PROMPT, sampling, self.N)
        engine2 = make_engine(cfg, params)
        cont = engine_generate(
            engine2, 0, PROMPT + full[:self.K],
            dataclasses.replace(sampling, rng_skip=self.K),
            self.N - self.K)
        assert cont == full[self.K:]


def run_scheduler_requests(engine, requests):
    """requests: list of GenRequest kwargs dicts. Returns (sched,
    events-per-request)."""
    sched = Scheduler(engine, debug_invariants=True)
    results = {i: [] for i in range(len(requests))}
    done = {i: threading.Event() for i in range(len(requests))}
    for i, kwargs in enumerate(requests):
        def emit(ev, i=i):
            results[i].append(ev)
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(emit=emit, id=f"r{i}", **kwargs))
    sched.start()
    for ev in done.values():
        assert ev.wait(120), "request did not complete"
    sched.stop()
    return sched, results


class TestSchedulerResumeAdmission:
    def test_resume_hits_radix_cache_and_books_counters(self, setup):
        """The cheap-resume contract: after an ordinary admission stored
        the prompt's blocks, a resume admission (prompt + emitted) HITS
        the radix cache (tokens_reused > 0 — seeded re-prefill, not full
        regeneration), books the sym_resume_* counters, and stamps the
        first event with the resume riders."""
        cfg, params = setup
        # One slot: the resume admits only after the first request
        # completed (and its admission stored the prompt blocks), so the
        # resume's lookup must hit — same serialization idiom as the
        # prefix-cache counter test.
        engine = make_engine(cfg, params, slots=1)
        sampling = SamplingParams()
        k = 5
        # The interrupted run: admits through the scheduler (populating
        # the radix tree with the prompt's whole blocks), emits k tokens.
        full = engine_generate(
            make_engine(cfg, params, cache_mb=0), 0, PROMPT, sampling, 10)
        sched, results = run_scheduler_requests(engine, [
            dict(prompt_ids=PROMPT, sampling=sampling, max_new_tokens=k),
            dict(prompt_ids=PROMPT + full[:k], sampling=sampling,
                 max_new_tokens=10 - k, resume_offset=k),
        ])
        stats = sched.stats()
        assert stats["resumes"] == 1
        assert stats["resumed_tokens"] == k
        # The resume admission reused at least the prompt's whole blocks
        # (the interrupted run's admission stored them).
        assert stats["resume_reused_tokens"] > 0
        first = results[1][0]
        assert first.resumed_from == k
        assert first.tokens_reused and first.tokens_reused > 0
        # And the continuation itself is the uninterrupted tail (token
        # ids, via tokens_generated accounting: 10 - k tokens total).
        last = results[1][-1]
        assert last.done and last.finish_reason in ("length", "stop")

    def test_non_resume_requests_book_nothing(self, setup):
        cfg, params = setup
        engine = make_engine(cfg, params, cache_mb=0)
        sched, _ = run_scheduler_requests(engine, [
            dict(prompt_ids=PROMPT, sampling=SamplingParams(),
                 max_new_tokens=3)])
        stats = sched.stats()
        assert stats["resumes"] == 0
        assert stats["resumed_tokens"] == 0
        assert stats["resume_reused_tokens"] == 0


class _StubScheduler:
    def __init__(self):
        self.submitted = []

    def submit(self, req):
        self.submitted.append(req)


class TestHostResumeParsing:
    """EngineHost._submit's resume leg: prompt extension, token-budget
    offset, RNG skip, and the derived-count fallback — no subprocess."""

    def _host(self):
        from types import SimpleNamespace

        host = EngineHost(config=None)
        host._engine = SimpleNamespace(tokenizer=ByteTokenizer(),
                                       prefix_block=0)
        host._scheduler = _StubScheduler()
        return host

    def test_resume_extends_prompt_and_offsets_budget(self):
        host = self._host()
        host._submit({"op": "submit", "id": "r1",
                      "messages": [{"role": "user", "content": "hi"}],
                      "max_new": 32,
                      "sampling": {"seed": 7},
                      "resume": {"text": "abcd", "tokens": 4}})
        (req,) = host._scheduler.submitted
        base = ByteTokenizer().apply_chat_template(
            [{"role": "user", "content": "hi"}])
        assert req.prompt_ids == base + list(b"abcd")
        assert req.max_new_tokens == 32 - 4
        assert req.resume_offset == 4
        assert req.sampling.rng_skip == 4
        assert req.sampling.seed == 7

    def test_resume_token_count_derived_from_text(self):
        host = self._host()
        host._submit({"op": "submit", "id": "r2",
                      "messages": [{"role": "user", "content": "hi"}],
                      "max_new": 32, "sampling": {},
                      "resume": {"text": "abcd"}})
        (req,) = host._scheduler.submitted
        assert req.resume_offset == 4  # byte tokenizer: 1 token per char
        assert req.max_new_tokens == 28

    def test_resume_exhausted_budget_completes_immediately(self):
        """The interrupted stream already spent max_tokens (only the
        finish frame was lost): the resume completes with a zero-token
        "length" finish instead of generating past the client's budget
        (which would also break identity with the uninterrupted run)."""
        host = self._host()
        writes = []
        host._write = lambda obj, events=0: writes.append(obj)
        host._submit({"op": "submit", "id": "r3",
                      "messages": [{"role": "user", "content": "hi"}],
                      "max_new": 3, "sampling": {},
                      "resume": {"text": "abcd", "tokens": 4}})
        assert host._scheduler.submitted == []  # never admitted
        (ev,) = writes
        assert ev["done"] and ev["finish_reason"] == "length"
        assert ev["tokens_new"] == 0 and ev["resume_from"] == 4

    def test_resume_negative_claim_rejected(self):
        host = self._host()
        writes = []
        host._write = lambda obj, events=0: writes.append(obj)
        host._submit({"op": "submit", "id": "r5",
                      "messages": [{"role": "user", "content": "hi"}],
                      "max_new": 8, "sampling": {},
                      "resume": {"text": "abcd", "tokens": -2}})
        assert host._scheduler.submitted == []
        (ev,) = writes
        assert ev["finish_reason"] == "error"
        assert "resume tokens" in ev["error"]

    def test_plain_submit_unchanged(self):
        host = self._host()
        host._submit({"op": "submit", "id": "r4",
                      "messages": [{"role": "user", "content": "hi"}],
                      "max_new": 8, "sampling": {}})
        (req,) = host._scheduler.submitted
        assert req.resume_offset == 0
        assert req.sampling.rng_skip == 0
        assert req.max_new_tokens == 8


class TestResumeJournal:
    def test_track_note_get_release(self):
        j = ResumeJournal()
        h = j.track("a")
        h.note(3)
        h.note(2)
        assert j.get("a") == 5
        assert j.get("missing") == 0
        h.release()
        assert j.get("a") == 0
        h.release()  # idempotent

    def test_merge_is_lower_bound(self):
        j = ResumeJournal()
        h = j.track("a")
        h.note(2)
        j.merge({"a": 7, "untracked": 9})
        assert j.get("a") == 7          # host journal ahead of relay
        assert j.get("untracked") == 0  # never tracked: not resurrected
        j.merge({"a": 3})
        assert j.get("a") == 7          # max-merge, never regresses
        h.release()


# --------------------------------------------------------------------
# Backend ⇄ fake host: the wire path (crash stamps, resume stream,
# offset dedup) — same harness as tests/test_supervisor.py.


class FakeHostBackend(TpuNativeBackend):
    def _host_argv(self, cfg_path):
        return [sys.executable, FAKE_HOST, cfg_path]


def fake_cfg(faults=None, fake_host=None):
    supervisor = {"heartbeat_s": 0.2, "wedge_timeout_s": 1.0,
                  "backoff_base_s": 0.05, "backoff_max_s": 0.2,
                  "max_respawns": 2, "spawn_timeout_s": 15.0,
                  "stop_grace_s": 0.5}
    return ConfigManager(config={
        "name": "resume-prov", "public": False, "serverKey": "00" * 32,
        "modelName": "fake:resume", "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "tpu": {"engine_isolation": "process", "max_batch_size": 4,
                "supervisor": supervisor},
        **({"faults": faults} if faults else {}),
        **({"fakeHost": fake_host} if fake_host else {}),
    })


def run(coro):
    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(coro, 60))


async def collect(backend, request):
    parts = []
    async for chunk in backend.stream(request):
        if chunk.text:
            parts.append(chunk.text)
    return parts


class TestBackendResume:
    def test_crash_shed_carries_journal_emitted(self):
        """Supervisor crash mid-stream: the restarting shed's `emitted`
        stamp equals the tokens this stream actually relayed — the
        client's resume anchor. (Write arithmetic: startup = ready +
        clock×5 = 6 writes; nth=11 kills the host on the stream's 5th
        event, so 4 full events relayed before the crash.)"""
        cfg = fake_cfg(faults={"host.pipe_write": "crash@nth=11"})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                got = []
                with pytest.raises(BackendRestartingError) as exc_info:
                    async for chunk in backend.stream(InferenceRequest(
                            messages=[{"role": "user", "content": "x"}],
                            max_tokens=40)):
                        if chunk.text:
                            got.append(chunk.text)
                assert got, "crash landed before anything streamed"
                assert exc_info.value.emitted == len(got)
            finally:
                await backend.stop()

        run(main())

    def test_resume_streams_continuation_only(self):
        """A resume submit against the fake host yields only t{R}… and
        the backend books resumes/resumed/reused (tokens_reused > 0 on
        the resume admission — the acceptance-gate counter)."""
        cfg = fake_cfg()

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                full = await collect(backend, InferenceRequest(
                    messages=[{"role": "user", "content": "x"}],
                    max_tokens=9))
                assert full == [f"t{i} " for i in range(8)]
                cont = await collect(backend, InferenceRequest(
                    messages=[{"role": "user", "content": "x"}],
                    max_tokens=9,
                    resume_text="".join(full[:3]), resume_tokens=3))
                assert cont == full[3:], cont
                assert backend.resume_stats["resumes"] == 1
                assert backend.resume_stats["resumed_tokens"] == 3
                assert backend.resume_stats["reused_tokens"] > 0
                assert backend.resume_stats["dedup_dropped"] == 0
                stats = await backend.engine_stats()
                assert stats["resume"]["resumes"] == 1
            finally:
                await backend.stop()

        run(main())

    def test_offset_dedup_drops_overlap(self):
        """The host rewinds its continuation 2 tokens below the client's
        count (fakeHost.resumeOverlap) — the relay's offset dedup drops
        exactly the overlap, so the client never sees a replayed token."""
        cfg = fake_cfg(fake_host={"resumeOverlap": 2})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                full = [f"t{i} " for i in range(8)]
                cont = await collect(backend, InferenceRequest(
                    messages=[{"role": "user", "content": "x"}],
                    max_tokens=9,
                    resume_text="".join(full[:4]), resume_tokens=4))
                assert cont == full[4:], cont
                assert backend.resume_stats["dedup_dropped"] == 2
            finally:
                await backend.stop()

        run(main())

    def test_inproc_resume_continues_not_regenerates(self):
        """engine_isolation: inproc honors resume too (supports_resume
        is a class attribute, so the provider accepts resumes against
        this branch): the continuation stream carries exactly
        max_new − R tokens — a from-token-0 regeneration would emit the
        full budget and corrupt the client's splice."""
        cfg = ConfigManager(config={
            "name": "resume-inproc", "public": False,
            "serverKey": "00" * 32, "modelName": "tiny:resume",
            "apiProvider": "tpu_native", "dataCollectionEnabled": False,
            "tpu": {"engine_isolation": "inproc", "model_preset": "tiny",
                    "dtype": "float32", "max_batch_size": 2,
                    "max_seq_len": 128, "prefill_buckets": [32, 64],
                    "decode_block": 1, "prefill_chunk": 8,
                    "prefix_cache_mb": 16},
        })

        async def main():
            backend = TpuNativeBackend(cfg)
            await backend.start()
            try:
                full = []
                async for chunk in backend.stream(InferenceRequest(
                        messages=[{"role": "user", "content": "hi"}],
                        max_tokens=12)):
                    if chunk.tokens:
                        full.append(chunk)
                full_text = "".join(c.text for c in full)
                n_full = sum(c.tokens for c in full)
                cont_tokens = 0
                async for chunk in backend.stream(InferenceRequest(
                        messages=[{"role": "user", "content": "hi"}],
                        max_tokens=12, resume_text=full_text[:4],
                        resume_tokens=5)):
                    cont_tokens += chunk.tokens or 0
                # Budget honored: 12 − 5 = 7 tokens max (fewer only on
                # an early EOS, which the full run would have hit too).
                assert cont_tokens <= 12 - 5, cont_tokens
                assert n_full > cont_tokens
                assert backend.resume_stats["resumes"] == 1
                assert backend.resume_stats["resumed_tokens"] == 5
                stats = await backend.engine_stats()
                assert stats["resumes"] == 1
                assert stats["resumed_tokens"] == 5
                assert stats["resume"]["resumes"] == 1
            finally:
                await backend.stop()

        run(main())

    def test_journal_heartbeat_merge(self):
        """The host's stats-journal rider reaches the backend journal
        through the supervisor heartbeat: after a few relayed events the
        journal's count for the live stream is > 0 (and the entry is
        gone once the stream finishes)."""
        cfg = fake_cfg(fake_host={"tokenDelayS": 0.05})

        async def main():
            backend = FakeHostBackend(cfg)
            await backend.start()
            try:
                req = InferenceRequest(
                    messages=[{"role": "user", "content": "x"}],
                    max_tokens=30)
                seen = []
                agen = backend.stream(req)
                async for chunk in agen:
                    if chunk.text:
                        seen.append(chunk.text)
                    if len(seen) >= 4:
                        break
                # Mid-stream: the journal holds the relayed count.
                live = [k for k in backend._journal._emitted]
                assert live and backend._journal.get(live[0]) >= 4
                await agen.aclose()
                await asyncio.sleep(0.1)
                assert not backend._journal._emitted  # released
            finally:
                await backend.stop()

        run(main())
