"""Block-granular emit path + admission FIFO, on a fake engine.

White-box scheduler tests that need no JAX device work: a FakeEngine
implements the engine contract the Scheduler drives (prefill/insert,
block dispatch, slot accounting), so block processing and admission
order are exercised deterministically by calling the scheduler's
internals directly — no engine thread, no timing races.

Covers the perf-PR contracts:
  - ONE emit flush per decode block carrying every active slot's delta
    (the O(1)-writes-per-block property the batched host frame rides on)
  - vectorized finish scan fidelity: EOS mid-block, token-budget finish,
    EOS-at-budget-boundary precedence, cancel-mid-block discard
  - budget-deferred admissions drain in arrival order (FIFO), not from
    the inbox tail
"""

import numpy as np

from symmetry_tpu.engine.engine import SamplingParams
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer


class FakeEngine:
    """The scheduler-facing engine contract, minus the device."""

    def __init__(self, slots=8, block=8, capacity=4096,
                 buckets=(16, 32), batch_cap=4):
        self.max_slots = slots
        self.decode_block = block
        self.slot_capacity = capacity
        self.tokenizer = ByteTokenizer()
        self.prefill_buckets = buckets
        self._batch_cap = batch_cap
        self.prefill_order: list[bytes] = []  # prompts, in dispatch order
        self.released: list[int] = []

    def bucket_for(self, n):
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket")

    def prefill_batches_for(self, bucket):
        return (self._batch_cap,)

    def prefill_and_insert(self, slot, ids, sampling):
        self.prefill_order.append(bytes(ids))
        return ord("A")

    def prefill_and_insert_many(self, group):
        firsts = []
        for _slot, ids, _sampling in group:
            self.prefill_order.append(bytes(ids))
            firsts.append(ord("A"))
        return firsts

    def decode_steps_dispatch(self):  # pragma: no cover — loop not started
        raise AssertionError("tests drive _process_block directly")

    def release_slot(self, slot):
        self.released.append(slot)

    def slot_length(self, slot):
        return 0


def make_scheduler(eng, **kw):
    batches = []
    sched = Scheduler(eng, emit_batch=batches.append, **kw)
    return sched, batches


def submit(sched, prompt: bytes, max_new=100, cancelled=None):
    sched.submit(GenRequest(
        prompt_ids=list(prompt), sampling=SamplingParams(),
        max_new_tokens=max_new, emit=lambda ev: None,
        cancelled=cancelled or (lambda: False), id=prompt.decode()))


def events_of(batch, req_id):
    return [ev for req, ev in batch if req.id == req_id]


class TestBatchedBlockEmit:
    def test_one_flush_per_block_for_all_slots(self):
        """3 active slots × an 8-token block must leave as ONE emit flush
        with one coalesced event per slot — not 24 per-token emits."""
        eng = FakeEngine(slots=4, block=8)
        sched, batches = make_scheduler(eng)
        for rid in (b"r0", b"r1", b"r2"):
            submit(sched, rid)
        sched._admit_new()
        sched._flush_events()
        assert len(batches) == 1  # activation: 3 first tokens, 1 flush
        assert len(batches[0]) == 3

        toks = np.full((8, 4), ord("b"), dtype=np.int32)
        sched._process_block(toks, dict(sched._slots))
        sched._flush_events()
        assert len(batches) == 2
        block_batch = batches[1]
        assert len(block_batch) == 3  # one event per slot, whole block
        for _req, ev in block_batch:
            assert ev.text == "b" * 8
            assert ev.tokens_generated == 9  # 1 (prefill) + 8 (block)
            assert ev.tokens_emitted == 9    # all 9 streamed as text
            assert not ev.done
        assert sched.metrics["emit_flushes"] == 2
        assert sched.metrics["emit_events"] == 6
        # tokens counts EMITTED tokens: 3 activation firsts + 24 block
        assert sched.metrics["tokens"] == 27

    def test_eos_mid_block_finishes_and_discards_remainder(self):
        eng = FakeEngine(slots=2, block=8)
        sched, batches = make_scheduler(eng)
        submit(sched, b"r0")
        submit(sched, b"r1")
        sched._admit_new()
        sched._flush_events()
        toks = np.full((8, 2), ord("b"), dtype=np.int32)
        slot0 = next(s for s, a in sched._slots.items() if a.req.id == "r0")
        toks[3, slot0] = ByteTokenizer.EOS
        sched._process_block(toks, dict(sched._slots))
        sched._flush_events()
        (ev0,) = events_of(batches[-1], "r0")
        assert ev0.done and ev0.finish_reason == "stop"
        assert ev0.text == "bbb"          # tokens past the EOS discarded
        assert ev0.tokens_generated == 5  # 1 + 3 text + the EOS token
        assert ev0.tokens_emitted == 4    # …but only 4 ever streamed
        (ev1,) = events_of(batches[-1], "r1")
        assert not ev1.done and ev1.text == "b" * 8
        assert slot0 in eng.released and slot0 in sched._free
        # The 4 tokens the block produced past r0's EOS (and the EOS
        # itself) are discarded AND uncounted: 2 activation firsts +
        # 3 pushed for r0 + 8 for r1 — the number that matches what a
        # client could actually stream (bench tokens_streamed).
        assert sched.metrics["tokens"] == 13

    def test_token_budget_finishes_mid_block(self):
        eng = FakeEngine(slots=1, block=8)
        sched, batches = make_scheduler(eng)
        submit(sched, b"r0", max_new=5)  # 1 at prefill + 4 in the block
        sched._admit_new()
        sched._flush_events()
        toks = np.full((8, 1), ord("b"), dtype=np.int32)
        sched._process_block(toks, dict(sched._slots))
        sched._flush_events()
        (ev,) = events_of(batches[-1], "r0")
        assert ev.done and ev.finish_reason == "length"
        assert ev.text == "bbbb"
        assert ev.tokens_generated == 5

    def test_eos_wins_at_budget_boundary(self):
        """An EOS on the budget-exhausting token finishes as "stop" —
        EOS is checked before the length bound, like the per-token loop
        this pass replaced."""
        eng = FakeEngine(slots=1, block=8)
        sched, batches = make_scheduler(eng)
        submit(sched, b"r0", max_new=4)  # budget: 3 block tokens
        sched._admit_new()
        sched._flush_events()
        toks = np.full((8, 1), ord("b"), dtype=np.int32)
        toks[2, 0] = ByteTokenizer.EOS  # the 3rd = budget-exhausting token
        sched._process_block(toks, dict(sched._slots))
        sched._flush_events()
        (ev,) = events_of(batches[-1], "r0")
        assert ev.done and ev.finish_reason == "stop"
        assert ev.text == "bb" and ev.tokens_generated == 4

    def test_cancel_mid_block_discards_block(self):
        eng = FakeEngine(slots=1, block=8)
        sched, batches = make_scheduler(eng)
        cancelled = []
        submit(sched, b"r0", cancelled=lambda: bool(cancelled))
        sched._admit_new()
        sched._flush_events()
        tokens_before = sched.metrics["tokens"]
        cancelled.append(True)  # lands between dispatch and processing
        toks = np.full((8, 1), ord("b"), dtype=np.int32)
        sched._process_block(toks, dict(sched._slots))
        sched._flush_events()
        (ev,) = events_of(batches[-1], "r0")
        assert ev.done and ev.finish_reason == "cancelled"
        assert ev.text == "" and ev.token_id is None
        assert ev.tokens_generated == 1       # nothing from this block
        assert sched.metrics["tokens"] == tokens_before
        assert not sched._slots and 0 in eng.released

    def test_multibyte_held_across_blocks(self):
        """A UTF-8 codepoint split across two decode blocks must emit
        whole, on the block that completes it (push_many back-off)."""
        eng = FakeEngine(slots=1, block=2)
        sched, batches = make_scheduler(eng)
        submit(sched, b"r0")
        sched._admit_new()
        sched._flush_events()
        two = "é".encode()  # 2-byte codepoint
        block1 = np.array([[ord("x")], [two[0]]], dtype=np.int32)
        sched._process_block(block1, dict(sched._slots))
        sched._flush_events()
        (ev1,) = events_of(batches[-1], "r0")
        assert ev1.text == "x"  # the dangling first byte held back
        block2 = np.array([[two[1]], [ord("y")]], dtype=np.int32)
        sched._process_block(block2, dict(sched._slots))
        sched._flush_events()
        (ev2,) = events_of(batches[-1], "r0")
        assert ev2.text == "éy"


class TestDeferredAdmissionFifo:
    def test_deferred_subgroups_keep_arrival_order(self):
        """A budget-deferred subgroup must be admitted BEFORE requests
        that arrived after it — the old inbox-tail re-queue put r2/r4
        behind r5/r6 on every deferral."""
        # Budget ~0: the first prefill dispatch exhausts it, so a group
        # spanning two buckets defers its second unit.
        eng = FakeEngine(slots=8, block=4, batch_cap=4)
        sched, batches = make_scheduler(
            eng, admit_seconds_per_block=1e-9)
        submit(sched, b"occ")       # occupier engages the admission budget
        sched._admit_new()
        assert len(sched._slots) == 1

        short, long = b"r1", b"r3"  # bucket 16
        l2, l4, l5, l6 = (b"x2" + b"x" * 18, b"x4" + b"x" * 18,
                          b"x5" + b"x" * 18, b"x6" + b"x" * 18)  # bucket 32
        for p in (short, l2, long, l4, l5, l6):  # arrival order
            submit(sched, p)

        sched._spent_this_block = 0.0
        sched._admit_new()
        # group [r1, l2, r3, l4] split by bucket: unit [r1, r3] dispatched,
        # unit [l2, l4] deferred on the exhausted budget
        assert [bytes(r.prompt_ids) for r in sched._deferred] == [l2, l4]
        assert l5 not in eng.prefill_order and l2 not in eng.prefill_order

        sched._spent_this_block = 0.0
        sched._admit_new()
        order = eng.prefill_order
        # Deferred l2/l4 admit before the later arrivals l5/l6.
        assert order.index(l2) < order.index(l5)
        assert order.index(l4) < order.index(l5)
        assert order.index(l5) < order.index(l6)
        assert not sched._deferred

    def test_drain_condition_counts_deferred(self):
        """_admit_new must not report the queue drained while deferred
        requests wait (stop() would otherwise exit with work pending)."""
        eng = FakeEngine(slots=4, block=4, batch_cap=4)
        sched, _ = make_scheduler(eng, admit_seconds_per_block=1e-9)
        submit(sched, b"occ")
        sched._admit_new()
        submit(sched, b"s1")                 # bucket 16
        submit(sched, b"x" * 20)             # bucket 32 -> second unit
        sched._spent_this_block = 0.0
        drained = sched._admit_new()
        assert sched._deferred and drained is False
