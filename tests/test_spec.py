"""Speculative decoding subsystem (engine/spec/ + verify path).

Three layers, each tested at its own seam:

  - NGramDrafter: prompt-lookup proposal rules on plain lists (no JAX).
  - ops/sampling.verify_tokens + engine.verify_step: device-side
    acceptance — greedy lanes must reproduce the plain decode chain
    token-for-token whatever the drafter proposed.
  - Scheduler integration: the hard decode-equivalence requirement —
    greedy output with tpu.speculative ON is token-identical to OFF —
    plus ragged accepted-runs through the EOS/budget scan, counters, and
    the off-by-default contract (no drafter, no verify jit, no metrics).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symmetry_tpu.engine.engine import EngineError, InferenceEngine, SamplingParams
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.spec import NGramDrafter, SpecConfig
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import init_params, preset
from symmetry_tpu.ops.sampling import sample_tokens, verify_tokens


@pytest.fixture(scope="module")
def setup():
    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    return cfg, params


def make_engine(cfg, params, spec=None, slots=2, seq=128, block=4):
    return InferenceEngine(cfg, params, ByteTokenizer(), max_slots=slots,
                           max_seq_len=seq, prefill_buckets=(16, 32),
                           cache_dtype=jnp.float32, decode_block=block,
                           speculative=spec)


class TestSpecConfig:
    def test_knob_parsing(self):
        assert SpecConfig.from_knob(None) is None
        assert SpecConfig.from_knob(False) is None
        assert SpecConfig.from_knob(0) is None
        assert SpecConfig.from_knob(True) == SpecConfig()
        assert SpecConfig.from_knob(4).k_draft == 4
        parsed = SpecConfig.from_knob({"k_draft": 6, "ngram_max": 2})
        assert parsed.k_draft == 6 and parsed.ngram_max == 2
        with pytest.raises(ValueError, match="unknown"):
            SpecConfig.from_knob({"bogus": 1})
        with pytest.raises(ValueError):
            SpecConfig.from_knob("yes")
        with pytest.raises(ValueError):
            SpecConfig(k_draft=0)
        with pytest.raises(ValueError):
            SpecConfig(ngram_min=3, ngram_max=2)


class TestNGramDrafter:
    def test_no_match_proposes_nothing(self):
        d = NGramDrafter(SpecConfig(k_draft=4))
        d.begin(0, [1, 2, 3, 4, 5], 6)  # all tokens distinct
        assert d.propose(0) == []

    def test_matches_prior_occurrence(self):
        d = NGramDrafter(SpecConfig(k_draft=4, ngram_max=3))
        # context: 7 8 9 50 7 8 9 — suffix (7,8,9) recurs at the start,
        # so the draft is what followed it: 50 7 8 9.
        d.begin(0, [7, 8, 9, 50, 7, 8], 9)
        assert d.propose(0) == [50, 7, 8, 9]

    def test_longest_ngram_wins(self):
        d = NGramDrafter(SpecConfig(k_draft=2, ngram_max=2, ngram_min=1))
        # (5, 6) occurred with continuation (70, ...); a 1-gram (6,)
        # also occurred with continuation 80 — the 2-gram must win.
        d.begin(0, [5, 6, 70, 6, 80, 5], 6)
        assert d.propose(0) == [70, 6]

    def test_period_one_loop_drafts_full_width(self):
        """A token loop's newest prior occurrences sit inside the tail —
        the occurrence history must still supply a full k_draft run."""
        d = NGramDrafter(SpecConfig(k_draft=5, ngram_max=3))
        d.begin(0, [9] * 12, 9)
        assert d.propose(0) == [9] * 5

    def test_extend_and_release(self):
        d = NGramDrafter(SpecConfig(k_draft=3))
        d.begin(0, [1, 2, 3], 4)
        assert d.propose(0) == []
        d.extend(0, [1, 2, 3])  # suffix (1,2,3)... wait: ctx 1 2 3 4 1 2 3
        assert d.propose(0) == [4, 1, 2]
        d.release(0)
        assert d.propose(0) == []
        d.extend(0, [1, 2, 3])  # released slot: extend is a no-op
        assert d.propose(0) == []

    def test_long_prompt_indexing_is_bounded(self):
        """Admission indexing runs on the serving thread: a long prompt
        indexes only its last max_index_tokens — a match living solely
        in the dropped head is forfeited, one in the kept tail works."""
        d = NGramDrafter(SpecConfig(k_draft=3, max_index_tokens=16))
        head = [71, 72, 73, 74] + [200 + i for i in range(40)]
        d.begin(0, head + [5, 6, 7, 50, 51, 52, 5, 6], 7)
        assert len(d._ctx[0]) <= 17  # 16 prompt tail + first token
        assert d.propose(0) == [50, 51, 52]  # tail match still drafts
        d.extend(0, [71, 72])  # head-only ngram (71,72) has no match
        assert d.propose(0) == []

    def test_slots_are_independent(self):
        d = NGramDrafter(SpecConfig(k_draft=2))
        d.begin(0, [1, 1, 1, 1, 1], 1)
        d.begin(1, [2, 3, 4], 5)
        assert d.propose(0) == [1, 1]
        assert d.propose(1) == []


class TestVerifyTokens:
    """Acceptance math at the sampling-op level (no engine)."""

    def _dists(self, B, S, V, seed=0):
        logits = jax.random.normal(jax.random.key(seed), (B, S, V)) * 3.0
        return jnp.asarray(logits, jnp.float32)

    def test_greedy_accepts_exactly_matching_prefix(self):
        B, k, V = 3, 4, 50
        logits = self._dists(B, 1 + k, V)
        greedy = np.asarray(jnp.argmax(logits, -1))  # [B, S]
        draft = np.zeros((B, k), np.int32)
        # row 0: all correct; row 1: wrong at position 2; row 2: no drafts
        draft[0] = greedy[0, :k]
        draft[1] = greedy[1, :k]
        draft[1, 2] = (draft[1, 2] + 1) % V
        n_draft = np.array([k, k, 0], np.int32)
        out, n_emit = verify_tokens(
            logits, jnp.asarray(draft), jnp.asarray(n_draft),
            jax.random.split(jax.random.key(1), B),
            jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32))
        out, n_emit = np.asarray(out), np.asarray(n_emit)
        assert n_emit.tolist() == [k + 1, 3, 1]
        # every emitted token is the greedy chain token at its position
        for b in range(B):
            for j in range(n_emit[b]):
                assert out[b, j] == greedy[b, j]

    def test_zero_draft_matches_sample_tokens_greedy(self):
        """A no-proposal slot must advance exactly like a decode step."""
        B, V = 4, 32
        logits = self._dists(B, 1, V, seed=7)
        keys = jax.random.split(jax.random.key(2), B)
        want = np.asarray(sample_tokens(
            logits[:, 0], keys, jnp.zeros((B,)), jnp.ones((B,)),
            jnp.zeros((B,), jnp.int32)))
        out, n_emit = verify_tokens(
            logits, jnp.zeros((B, 0), jnp.int32),
            jnp.zeros((B,), jnp.int32), keys,
            jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32))
        assert np.asarray(n_emit).tolist() == [1] * B
        assert np.asarray(out)[:, 0].tolist() == want.tolist()

    def test_sampled_lane_emits_kept_tokens_only(self):
        """Temperature/top-k lanes: every emitted token must come from
        the top-k keep set (the masked target distribution)."""
        B, k, V = 8, 3, 64
        logits = self._dists(B, 1 + k, V, seed=3)
        top2 = np.asarray(jax.lax.top_k(logits, 2)[1])  # [B, S, 2]
        draft = np.asarray(
            jax.random.randint(jax.random.key(4), (B, k), 0, V), np.int32)
        out, n_emit = verify_tokens(
            logits, jnp.asarray(draft), jnp.full((B,), k, jnp.int32),
            jax.random.split(jax.random.key(5), B),
            jnp.full((B,), 0.8), jnp.ones((B,)),
            jnp.full((B,), 2, jnp.int32))
        out, n_emit = np.asarray(out), np.asarray(n_emit)
        for b in range(B):
            for j in range(n_emit[b]):
                assert out[b, j] in top2[b, j], (b, j)


class TestEngineVerify:
    def test_verify_step_reproduces_greedy_chain(self, setup):
        """Greedy + speculation must be token-identical to plain decode,
        for correct drafts, garbage drafts, and no drafts alike."""
        cfg, params = setup
        plain = make_engine(cfg, params, block=1)
        prompt = list(b"verify chain")
        ref = [plain.prefill_and_insert(0, prompt, SamplingParams())]
        for _ in range(11):
            ref.append(int(plain.decode_step()[0]))

        spec = SpecConfig(k_draft=4)
        eng = make_engine(cfg, params, spec=spec, block=1)
        got = [eng.prefill_and_insert(0, prompt, SamplingParams())]
        variants = [lambda nxt: (nxt, len(nxt)),            # true drafts
                    lambda nxt: ([1, 2, 3, 4], 4),          # garbage
                    lambda nxt: ([], 0)]                    # none
        i = 0
        while len(got) < 12:
            draft = np.zeros((2, 4), np.int32)
            n_draft = np.zeros((2,), np.int32)
            prop, n = variants[i % 3](ref[len(got):len(got) + 4])
            draft[0, :len(prop)] = prop
            n_draft[0] = n
            toks, n_emit = eng.verify_step(draft, n_draft)
            got.extend(int(toks[j, 0]) for j in range(int(n_emit[0])))
            i += 1
        assert got[:12] == ref

    def test_verify_interleaves_with_decode_blocks(self, setup):
        """Cache-length rollback: a rejected tail must leave the slot in
        a state plain block decode continues correctly from."""
        cfg, params = setup
        plain = make_engine(cfg, params, block=1)
        prompt = list(b"mixed mode")
        ref = [plain.prefill_and_insert(0, prompt, SamplingParams())]
        for _ in range(8):
            ref.append(int(plain.decode_step()[0]))

        eng = make_engine(cfg, params, spec=SpecConfig(k_draft=4), block=2)
        got = [eng.prefill_and_insert(0, prompt, SamplingParams())]
        draft = np.zeros((2, 4), np.int32)
        draft[0] = [9, 9, 9, 9]  # all rejected -> rollback to +1
        toks, n_emit = eng.verify_step(draft, np.array([4, 0], np.int32))
        got.extend(int(toks[j, 0]) for j in range(int(n_emit[0])))
        blk = eng.decode_steps()  # plain block rides the rolled-back cache
        got.extend(int(t) for t in blk[:, 0])
        draft[0] = ref[len(got):len(got) + 4]
        toks, n_emit = eng.verify_step(draft, np.array([4, 0], np.int32))
        got.extend(int(toks[j, 0]) for j in range(int(n_emit[0])))
        assert got[:9] == ref

    def test_disabled_engine_has_no_verify_path(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params)
        assert eng.spec is None
        assert not hasattr(eng, "_verify")
        with pytest.raises(EngineError, match="not enabled"):
            eng.verify_step(np.zeros((2, 4), np.int32),
                            np.zeros((2,), np.int32))

    def test_warmup_compiles_verify_only_when_enabled(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, spec=SpecConfig(k_draft=2))
        eng.warmup()  # must include the verify shape — no compile below
        prompt = list(b"after warmup")
        plain = make_engine(cfg, params, block=1)
        ref = [plain.prefill_and_insert(0, prompt, SamplingParams())]
        for _ in range(3):
            ref.append(int(plain.decode_step()[0]))
        got = [eng.prefill_and_insert(0, prompt, SamplingParams())]
        draft = np.zeros((2, 2), np.int32)
        draft[0] = ref[1:3]
        toks, n_emit = eng.verify_step(draft, np.array([2, 0], np.int32))
        got.extend(int(toks[j, 0]) for j in range(int(n_emit[0])))
        assert got == ref[:len(got)]

    def test_k_draft_must_fit_context(self, setup):
        cfg, params = setup
        with pytest.raises(EngineError, match="k_draft"):
            make_engine(cfg, params, spec=SpecConfig(k_draft=256), seq=64)


def run_scheduler_requests(engine, requests):
    sched = Scheduler(engine, debug_invariants=True)
    results = {i: [] for i in range(len(requests))}
    done = {i: threading.Event() for i in range(len(requests))}
    for i, (ids, sampling, max_new) in enumerate(requests):
        def emit(ev, i=i):
            results[i].append(ev)
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(prompt_ids=ids, sampling=sampling,
                                max_new_tokens=max_new, emit=emit,
                                id=f"r{i}"))
    sched.start()
    for ev in done.values():
        assert ev.wait(120), "request did not complete"
    sched.stop()
    return results, sched


def cycling_params(params):
    """Bias the LM head so greedy generation settles into one token —
    the n-gram drafter then matches constantly, exercising the verify
    path instead of the plain-block fallback."""
    lm = np.array(params["lm_head"])
    lm[:, 120] = 10.0
    out = dict(params)
    out["lm_head"] = jnp.asarray(lm)
    return out


class TestSchedulerSpeculative:
    def test_greedy_token_identical_on_off(self, setup):
        """THE acceptance gate: tpu.speculative on => greedy output
        byte-identical to off, with verify blocks actually exercised."""
        cfg, params = setup
        biased = cycling_params(params)
        prompts = [list(b"spec request one"), list(b"two!")]
        reqs = [(p, SamplingParams(), 30) for p in prompts]

        off, _ = run_scheduler_requests(make_engine(cfg, biased), reqs)
        on, sched = run_scheduler_requests(
            make_engine(cfg, biased, spec=SpecConfig(k_draft=4)), reqs)
        for i in range(len(prompts)):
            assert ("".join(ev.text for ev in on[i])
                    == "".join(ev.text for ev in off[i]))
            assert (on[i][-1].tokens_generated
                    == off[i][-1].tokens_generated)
            assert on[i][-1].finish_reason == off[i][-1].finish_reason
        spec = sched.stats()["speculative"]
        assert spec["verify_blocks"] > 0
        assert spec["accepted"] > 0
        assert spec["drafted"] == spec["accepted"] + spec["rolled_back"]
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
        # emitted-token accounting matches across modes too
        assert (sched.metrics["tokens"]
                == sum(on[i][-1].tokens_emitted for i in on))

    def test_no_proposals_keeps_overlapped_plain_path(self, setup):
        """Knob on but traffic that never drafts (random tiny-model
        output): every block must go through the plain double-buffered
        dispatch — zero verify dispatches, zero early syncs forced by
        the drafter (the peek predicts no proposal)."""
        cfg, params = setup
        engine = make_engine(cfg, params, spec=SpecConfig(k_draft=4))
        prompt = list(b"abcdefgh")  # distinct ids; generation is diverse
        on, sched = run_scheduler_requests(
            engine, [(prompt, SamplingParams(), 16)])
        off, _ = run_scheduler_requests(
            make_engine(cfg, params), [(prompt, SamplingParams(), 16)])
        assert ("".join(ev.text for ev in on[0])
                == "".join(ev.text for ev in off[0]))
        # No (or almost no) verify work: the plain path carried the run.
        assert sched.metrics["spec_drafted"] <= 4

    def test_off_means_off(self, setup):
        """Engine without the knob: no drafter, no spec stats block."""
        cfg, params = setup
        results, sched = run_scheduler_requests(
            make_engine(cfg, params),
            [(list(b"plain"), SamplingParams(), 8)])
        assert sched._drafter is None
        assert "speculative" not in sched.stats()
        assert sched.metrics["spec_verify_blocks"] == 0

    def test_per_request_opt_out(self, setup):
        """speculative=False requests never enter the drafter even when
        the engine knob is on."""
        cfg, params = setup
        biased = cycling_params(params)
        engine = make_engine(cfg, biased, spec=SpecConfig(k_draft=4))
        sched = Scheduler(engine, debug_invariants=True)
        evs, done = [], threading.Event()

        def emit(ev):
            evs.append(ev)
            if ev.done:
                done.set()

        sched.submit(GenRequest(
            prompt_ids=list(b"opted out"), sampling=SamplingParams(),
            max_new_tokens=24, emit=emit, id="o", speculative=False))
        sched.start()
        assert done.wait(120)
        sched.stop()
        assert evs[-1].done
        assert sched.metrics["spec_verify_blocks"] == 0
        assert sched.stats()["speculative"]["drafted"] == 0

    def test_eos_inside_accepted_run_finishes_stream(self, setup):
        """An EOS accepted mid-proposal must finish the stream at the
        EOS, discarding the accepted remainder — same rule as EOS inside
        a plain block."""
        cfg, params = setup
        eos = ByteTokenizer().EOS
        lm = np.array(params["lm_head"])
        lm[:, eos] = 10.0  # greedy emits EOS forever
        biased = dict(params)
        biased["lm_head"] = jnp.asarray(lm)
        results, _ = run_scheduler_requests(
            make_engine(cfg, biased, spec=SpecConfig(k_draft=4)),
            [(list(b"stop it"), SamplingParams(), 50)])
        last = results[0][-1]
        assert last.done and last.finish_reason == "stop"
        ref, _ = run_scheduler_requests(
            make_engine(cfg, biased),
            [(list(b"stop it"), SamplingParams(), 50)])
        assert last.tokens_generated == ref[0][-1].tokens_generated

    def test_budget_finish_with_speculation(self, setup):
        """max_new_tokens lands mid-accepted-run: finish as length with
        the exact budgeted count, like the plain-block budget scan."""
        cfg, params = setup
        biased = cycling_params(params)
        for budget in (7, 10):
            on, _ = run_scheduler_requests(
                make_engine(cfg, biased, spec=SpecConfig(k_draft=4)),
                [(list(b"budget"), SamplingParams(), budget)])
            off, _ = run_scheduler_requests(
                make_engine(cfg, biased),
                [(list(b"budget"), SamplingParams(), budget)])
            assert on[0][-1].tokens_generated == budget
            assert ("".join(ev.text for ev in on[0])
                    == "".join(ev.text for ev in off[0]))

    def test_backend_from_config_knob(self):
        """tpu.speculative flows provider.yaml → from_tpu_config →
        engine → scheduler drafter, through the inproc backend; warmup
        covers the verify shape; streaming works end to end."""
        import asyncio

        from symmetry_tpu.provider.backends.base import InferenceRequest
        from symmetry_tpu.provider.backends.tpu_native import (
            TpuNativeBackend)
        from symmetry_tpu.provider.config import ConfigManager

        cfg_mgr = ConfigManager(config={
            "name": "t", "public": False, "serverKey": "00" * 32,
            "modelName": "tiny-test", "apiProvider": "tpu_native",
            "tpu": {"model_preset": "tiny", "dtype": "float32",
                    "max_batch_size": 2, "max_seq_len": 64,
                    "prefill_buckets": [16, 32],
                    "engine_isolation": "inproc",
                    "speculative": {"k_draft": 3}},
        })

        async def drive():
            backend = TpuNativeBackend(cfg_mgr)
            await backend.start()
            assert backend._engine.spec is not None
            assert backend._engine.spec.k_draft == 3
            assert backend._scheduler._drafter is not None
            text = []
            async for ch in backend.stream(InferenceRequest(
                    messages=[{"role": "user", "content": "ping"}],
                    max_tokens=5)):
                text.append(ch.text)
            stats = backend._scheduler.stats()
            assert "speculative" in stats
            await backend.stop()
            return "".join(text)

        assert asyncio.run(asyncio.wait_for(drive(), 180)) is not None

    def test_seeded_sampled_stream_completes(self, setup):
        """Temperature lanes under speculation: the stream completes and
        every token is finite/valid (unbiasedness is the math's job —
        ops-level tests pin the keep-set property)."""
        cfg, params = setup
        biased = cycling_params(params)
        results, sched = run_scheduler_requests(
            make_engine(cfg, biased, spec=SpecConfig(k_draft=4)),
            [(list(b"sampled"), SamplingParams(temperature=0.9, seed=3),
              24)])
        last = results[0][-1]
        assert last.done and last.finish_reason in ("length", "stop")
        assert last.tokens_generated == 24
