"""Multi-host lockstep test: N real processes over jax.distributed (CPU).

Validates SURVEY §7 stage 6's rank-0 control plane: rank 0 drives the
engine through CommandLoop broadcasts, workers mirror every jitted call,
and all ranks' engines advance identically — the property that makes one
logical provider out of N JAX processes.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # multi-process / heavy-compile; run with -m ""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_lockstep():
    port = free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for rank in range(2)
    ]
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=280)
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["rank"]] = r
    assert set(results) == {0, 1}

    # Lockstep: both ranks saw identical tokens and identical final state.
    assert results[0]["tokens"] == results[1]["tokens"]
    assert results[0]["lengths"] == results[1]["lengths"]
    # Prefill (1) + 3 decode blocks of 2 = 7 generated; slot0 len = 10+7-1.
    assert results[0]["lengths"][0] == 16
    # 4 entries: first token + 3 decode blocks.
    assert len(results[0]["tokens"]) == 4


def test_multihost_provider_end_to_end():
    """Full system: server + rank-0 provider + client in one process, a
    worker rank following in another — one logical provider, two JAX
    processes, tensor-parallel over a 2-process mesh (BASELINE config 5
    in miniature)."""
    port = free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    repo = os.path.dirname(os.path.dirname(__file__))
    env["PYTHONPATH"] = repo

    import tempfile

    import yaml

    worker_cfg = {
        "name": "mh-prov", "public": False, "serverKey": "00" * 32,
        "modelName": "tiny:mh", "apiProvider": "tpu_native",
        "tpu": {
            "model_preset": "tiny", "dtype": "float32",
            "max_batch_size": 2, "max_seq_len": 64,
            "prefill_buckets": [32], "decode_block": 2,
            "mesh": {"model": 2},
            "multihost": {"coordinator": f"127.0.0.1:{port}",
                          "num_processes": 2, "process_id": 1,
                          "dcn_data": 2},
        },
    }
    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as fh:
        yaml.safe_dump(worker_cfg, fh)
        worker_cfg_path = fh.name

    worker_env = dict(env)
    worker_env["JAX_PLATFORMS"] = "cpu"
    worker_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    rank0 = subprocess.Popen(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "multihost_provider_rank0.py"),
         str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    worker = subprocess.Popen(
        [sys.executable, "-m", "symmetry_tpu.provider", "--worker",
         "-c", worker_cfg_path],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=worker_env, cwd=repo)

    out0, err0 = rank0.communicate(timeout=280)
    assert rank0.returncode == 0, f"rank0 failed:\n{err0[-3000:]}"
    outw, errw = worker.communicate(timeout=60)
    assert worker.returncode == 0, f"worker failed:\n{errw[-3000:]}"

    result = next(json.loads(l[len("RESULT "):])
                  for l in out0.splitlines() if l.startswith("RESULT "))
    assert result["ok"]
    assert result["text_len"] >= 0
    os.unlink(worker_cfg_path)
