"""symscale controller suite: the SLO-goodput autoscaler.

Two layers, mirroring the pool's own tests:

  - PoolAutoscaler UNIT suite against a pure-state PoolRouter with an
    injectable clock — every policy rule (burn spawn, queue spawn,
    dwell, churn cooldown, measured-ratio rebalance, floor/ceiling,
    idle drain) drives in microseconds with no sleeps.
  - Chip-second accounting on the router (the goodput denominator).
  - A fake-host pool E2E: a real TpuNativeBackend in pool mode over
    protocol-faithful fake engine hosts, where an SLO burn spike makes
    the autoscaler SPAWN a real prefill member mid-traffic with zero
    in-flight sheds — the telemetry → topology loop closed end to end.
"""

import asyncio
import os
import sys
import time
import uuid

from symmetry_tpu.engine.disagg.autoscale import (
    AutoscaleConfig,
    PoolAutoscaler,
)
from symmetry_tpu.engine.disagg.pool import MemberState, PoolRouter
from symmetry_tpu.utils.metrics import SloMonitor

FAKE_HOST = os.path.join(os.path.dirname(__file__), "fake_host.py")


def run_async(coro, timeout=60):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def _pool(t, m_prefill=1, n_decode=1):
    """Healthy pool on an injectable clock (`t` is a one-element list)."""
    r = PoolRouter(clock=lambda: t[0])
    for i in range(m_prefill):
        r.add_member(f"p{i}", "prefill")
        r.mark_healthy(f"p{i}")
    for i in range(n_decode):
        r.add_member(f"d{i}", "decode")
        r.mark_healthy(f"d{i}")
    return r


def _asc(t, router, **overrides):
    cfg = {"dwell_s": 10.0, "churn_cooldown_s": 60.0, "max_members": 4,
           "drain_ticks": 3, **overrides}
    return PoolAutoscaler(AutoscaleConfig(cfg), router,
                          clock=lambda: t[0])


class TestAutoscalerSpawn:
    def test_ttft_burn_spawns_prefill(self):
        t = [0.0]
        asc = _asc(t, _pool(t))
        d = asc.tick(burn={"ttft": 5.0})
        assert d["action"] == "spawn" and d["tier"] == "prefill"
        assert asc.counters["spawns"] == 1
        assert asc.target == {"prefill": 2, "decode": 1}

    def test_inter_chunk_burn_spawns_decode(self):
        t = [0.0]
        asc = _asc(t, _pool(t))
        d = asc.tick(burn={"inter_chunk": 3.0})
        assert d["action"] == "spawn" and d["tier"] == "decode"

    def test_worse_pressure_wins(self):
        t = [0.0]
        asc = _asc(t, _pool(t))
        d = asc.tick(burn={"ttft": 2.0, "inter_chunk": 8.0})
        assert d["tier"] == "decode"

    def test_queue_load_spawns_without_burn(self):
        # The load gauge is an instant sample (burn is already a
        # windowed rate): a queue spawn needs spawn_queue_ticks
        # consecutive over-threshold ticks, not one spike.
        t = [0.0]
        r = _pool(t)
        r.update_gauges("p0", queue_depth=5.0)
        asc = _asc(t, r)
        for _ in range(2):
            assert asc.tick()["action"] == "hold"
            t[0] += 0.5
        d = asc.tick()
        assert d["action"] == "spawn" and d["tier"] == "prefill"
        assert d["inputs"]["avg_load"]["prefill"] == 5.0

    def test_transient_queue_spike_never_spawns(self):
        # A clump that drains within a heartbeat resets the pressure
        # streak — no member boot for a queue that already vanished.
        t = [0.0]
        r = _pool(t)
        asc = _asc(t, r)
        for _ in range(6):
            r.update_gauges("p0", queue_depth=5.0)
            assert asc.tick()["action"] == "hold"
            t[0] += 0.5
            r.update_gauges("p0", queue_depth=0.0)
            assert asc.tick()["action"] == "hold"
            t[0] += 0.5
        assert asc.counters["spawns"] == 0
        assert asc.stats()["press_ticks"]["prefill"] == 0

    def test_ceiling_blocks_spawn(self):
        t = [0.0]
        asc = _asc(t, _pool(t), max_members=1)
        d = asc.tick(burn={"ttft": 9.0})
        assert d["action"] == "hold"
        assert asc.counters["spawns"] == 0

    def test_remote_peers_never_grow_prefill(self):
        t = [0.0]
        asc = PoolAutoscaler(AutoscaleConfig({"dwell_s": 0.0}),
                             _pool(t), clock=lambda: t[0],
                             grow_prefill=False)
        d = asc.tick(burn={"ttft": 9.0})
        assert d["action"] == "hold"
        # decode pressure still actuates
        d = asc.tick(burn={"inter_chunk": 9.0})
        assert d["action"] == "spawn" and d["tier"] == "decode"


class TestAutoscalerHysteresis:
    def test_dwell_gates_consecutive_actions(self):
        t = [0.0]
        asc = _asc(t, _pool(t), dwell_s=10.0)
        assert asc.tick(burn={"ttft": 5.0})["action"] == "spawn"
        t[0] = 1.0
        d = asc.tick(burn={"ttft": 5.0})
        assert d["action"] == "hold" and "dwell" in d["reason"]
        assert asc.counters["dwell_holds"] == 1
        t[0] = 11.0
        assert asc.tick(burn={"ttft": 5.0})["action"] == "spawn"
        assert asc.counters["spawns"] == 2

    def test_churn_cooldown_pauses_scaling(self):
        t = [0.0]
        asc = _asc(t, _pool(t), churn_cooldown_s=60.0)
        asc.note_churn()
        t[0] = 1.0
        d = asc.tick(burn={"ttft": 9.0})
        assert d["action"] == "hold" and d["reason"] == "churn_cooldown"
        assert asc.counters["cooldown_holds"] == 1
        t[0] = 61.0
        assert asc.tick(burn={"ttft": 9.0})["action"] == "spawn"

    def test_churn_is_not_a_scaling_decision(self):
        """A supervisor respawn must never inflate the decision
        counter — symtop's SCALE column means 'the shape moved'."""
        t = [0.0]
        asc = _asc(t, _pool(t))
        asc.note_churn()
        asc.note_churn()
        assert asc.counters["churn_cooldowns"] == 2
        assert asc.counters["spawns"] == 0
        assert asc.counters["drains"] == 0
        assert asc.decision_log() == []  # records come from ticks only

    def test_applying_holds(self):
        t = [0.0]
        asc = _asc(t, _pool(t))
        d = asc.tick(burn={"ttft": 9.0}, applying=True)
        assert d["action"] == "hold"
        assert d["reason"] == "applying_previous_decision"


class TestAutoscalerDrain:
    def test_idle_tier_drains_idlest_member(self):
        t = [0.0]
        r = _pool(t, m_prefill=2)
        r.update_gauges("p0", queue_depth=1.0)  # p1 is the idlest
        asc = _asc(t, r, drain_ticks=3, drain_load=1.0)
        for i in range(2):
            t[0] = float(i)
            assert asc.tick()["action"] == "hold"
        t[0] = 2.0
        d = asc.tick()
        assert d["action"] == "drain"
        assert d["tier"] == "prefill" and d["member"] == "p1"
        assert asc.target["prefill"] == 1

    def test_floor_never_drains_last_member(self):
        t = [0.0]
        asc = _asc(t, _pool(t), drain_ticks=2)
        for i in range(8):
            t[0] = float(i)
            assert asc.tick()["action"] == "hold"
        assert asc.counters["drains"] == 0

    def test_applying_freezes_idle_streak(self):
        # A member boot takes seconds of heartbeats; the tier must not
        # bank idleness credit while the spawn is still in flight, or
        # the new member is drained the instant it joins.
        t = [0.0]
        asc = _asc(t, _pool(t, m_prefill=2), drain_ticks=3)
        for i in range(10):
            t[0] = float(i)
            assert asc.tick(applying=True)["action"] == "hold"
        assert asc.counters["drains"] == 0
        for i in range(3):
            t[0] = 20.0 + i
            d = asc.tick()
        assert d["action"] == "drain"

    def test_membership_change_resets_idle_streak(self):
        # A tier whose membership just changed restarts observation:
        # the fresh topology earns a full drain_ticks window before the
        # idlest member can be given back.
        t = [0.0]
        r = _pool(t, m_prefill=2)
        asc = _asc(t, r, drain_ticks=3)
        for i in range(2):
            t[0] = float(i)
            asc.tick()
        r.add_member("p9", "prefill")
        r.mark_healthy("p9")
        t[0] = 20.0
        assert asc.tick()["action"] == "hold"  # streak reset on join
        for i in range(3):
            t[0] = 21.0 + i
            d = asc.tick()
        assert d["action"] == "drain"
        assert asc.counters["drains"] == 1

    def test_burning_tier_is_not_idle(self):
        t = [0.0]
        asc = _asc(t, _pool(t, m_prefill=2), drain_ticks=2,
                   max_members=2)
        for i in range(6):
            t[0] = float(i)
            # burn below spawn threshold but above the idle cutoff
            # (spawn_burn/2): the streak must never start
            d = asc.tick(burn={"ttft": 0.8})
        assert d["action"] == "hold"
        assert asc.counters["drains"] == 0


class TestAutoscalerRebalance:
    def test_measured_ratio_moves_a_member(self):
        t = [0.0]
        asc = _asc(t, _pool(t, m_prefill=2, n_decode=2))
        d = asc.tick(busy_delta_s={"prefill": 0.9, "decode": 0.1})
        assert d["action"] == "rebalance"
        assert d["spawn_tier"] == "prefill"
        assert d["drain_tier"] == "decode"
        assert d["member"] in ("d0", "d1")
        assert asc.counters["rebalances"] == 1
        assert asc.target == {"prefill": 3, "decode": 1}

    def test_balanced_ratio_holds(self):
        t = [0.0]
        asc = _asc(t, _pool(t, m_prefill=2, n_decode=2))
        d = asc.tick(busy_delta_s={"prefill": 0.5, "decode": 0.5})
        assert d["action"] == "hold"

    def test_noise_floor_gates_rebalance(self):
        t = [0.0]
        asc = _asc(t, _pool(t, m_prefill=2, n_decode=2),
                   min_busy_s=0.5)
        d = asc.tick(busy_delta_s={"prefill": 0.01, "decode": 0.001})
        assert d["action"] == "hold"

    def test_loaded_shrink_tier_blocks_rebalance(self):
        t = [0.0]
        r = _pool(t, m_prefill=2, n_decode=2)
        r.update_gauges("d0", queue_depth=1.0)
        r.update_gauges("d1", queue_depth=1.0)  # decode busy: avg 1.0
        asc = _asc(t, r)
        d = asc.tick(busy_delta_s={"prefill": 0.9, "decode": 0.1})
        assert d["action"] == "hold"


class TestDecisionRecords:
    def test_every_tick_books_a_record(self):
        t = [0.0]
        asc = _asc(t, _pool(t))
        asc.tick()
        asc.tick(burn={"ttft": 9.0})
        log = asc.decision_log()
        assert [d["action"] for d in log] == ["hold", "spawn"]
        for d in log:
            assert {"t", "action", "reason", "inputs",
                    "chip_s", "goodput_tokens_per_chip_s"} <= set(d)
        assert log[1]["inputs"]["burn"]["prefill"] == 9.0

    def test_goodput_at_decision(self):
        t = [0.0]
        r = _pool(t)
        t[0] = 10.0  # 2 members alive 10 s → 20 chip-seconds
        asc = _asc(t, r)
        d = asc.tick(tokens_total=100.0)
        assert d["chip_s"] == 20.0
        assert d["goodput_tokens_per_chip_s"] == 5.0

    def test_stats_shape(self):
        t = [0.0]
        asc = _asc(t, _pool(t))
        asc.tick()
        st = asc.stats()
        assert st["ticks"] == 1 and st["holds"] == 1
        assert st["target"] == {"prefill": 1, "decode": 1}
        assert st["config"]["max_members"] == 4
        assert len(st["decisions"]) == 1
        assert "inputs" not in st["decisions"][0]  # stats tail is slim
        assert st["actions"] == []  # holds never make the action tail


class TestChipSeconds:
    def test_alive_time_accumulates_and_loss_pauses(self):
        t = [0.0]
        r = PoolRouter(clock=lambda: t[0])
        r.add_member("p0", "prefill")
        r.mark_healthy("p0")
        t[0] = 10.0
        assert r.chip_seconds() == 10.0
        r.on_lost("p0")
        t[0] = 15.0
        assert r.chip_seconds() == 10.0  # lost members burn no chip
        r.mark_healthy("p0")  # rejoin reopens the interval
        t[0] = 18.0
        assert r.chip_seconds() == 13.0

    def test_retire_banks_chip_seconds(self):
        t = [0.0]
        r = _pool(t)
        t[0] = 5.0
        assert r.retire("d0") is True
        assert r.get("d0") is None
        t[0] = 50.0
        # retired member's 5 s stay banked; p0 keeps accumulating
        assert r.chip_seconds() == 55.0
        assert r.counters["retires"] == 1
        st = r.stats()
        assert st["chip_seconds"] == 55.0
        assert set(st["members"]) == {"p0"}

    def test_retire_refused_while_in_flight(self):
        t = [0.0]
        r = _pool(t)
        r.place("r1")
        assert r.retire("p0") is False
        r.note_done("r1")
        assert r.retire("p0") is True


# ---------------------------------------------------------------------
# E2E: the loop closed through the real backend against fake hosts — an
# SLO burn spike spawns a REAL prefill member (node + link + membership)
# mid-traffic, with zero in-flight sheds.


def _autoscale_backend(pool, autoscale, *, token_delay_s=0.05):
    from symmetry_tpu.engine.disagg.node import PrefillNode
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
    from symmetry_tpu.provider.config import ConfigManager

    class FakePoolBackend(TpuNativeBackend):
        def _host_argv(self, cfg_path):
            return [sys.executable, FAKE_HOST, cfg_path]

        def _node_factory(self, config, listen):
            node = PrefillNode(config, listen=listen)
            node._host_argv = lambda p: [sys.executable, FAKE_HOST, p]
            return node

    cfg = ConfigManager(config={
        "name": "scale-fake", "public": False, "serverKey": "00" * 32,
        "modelName": "fake:scale", "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "fakeHost": {"tokenDelayS": token_delay_s},
        "tpu": {"engine_isolation": "process", "max_batch_size": 4,
                "role": "disagg",
                "autoscale": autoscale,
                "supervisor": {"heartbeat_s": 30.0, "wedge_timeout_s": 5.0,
                               "backoff_base_s": 0.05, "backoff_max_s": 0.2,
                               "max_respawns": 2, "spawn_timeout_s": 15.0,
                               "stop_grace_s": 0.5, "min_stable_s": 0.2},
                "disagg": {"peer": f"mem://scale-{uuid.uuid4().hex[:8]}",
                           "reconnect_base_s": 0.05,
                           "pool": pool}},
    })
    return FakePoolBackend(cfg)


async def _collect_stream(backend, content, max_tokens=4):
    from symmetry_tpu.provider.backends.base import InferenceRequest

    text = []
    async for chunk in backend.stream(InferenceRequest(
            messages=[{"role": "user", "content": content}],
            max_tokens=max_tokens, temperature=0.0)):
        if chunk.text:
            text.append(chunk.text)
    return "".join(text)


class TestAutoscaleBackendFake:
    def test_burn_spike_spawns_member_with_zero_sheds(self):
        async def main():
            backend = _autoscale_backend(
                {"prefill": 1, "decode": 1, "heartbeat_s": 0.15},
                {"max_members": 2, "dwell_s": 0.2,
                 "churn_cooldown_s": 10.0, "drain_ticks": 10_000})
            await backend.start()
            try:
                # The provider's SLO monitor, exactly as provider.py
                # attaches it; a burst of over-target TTFTs lights the
                # fast-window burn the heartbeat feeds the controller.
                monitor = SloMonitor({"ttft_s": 0.005, "objective": 0.9,
                                      "fast_window_s": 5.0})
                backend.attach_slo_monitor(monitor)
                for _ in range(12):
                    monitor.observe("ttft", 0.5)
                tasks = [asyncio.ensure_future(
                    _collect_stream(backend, f"req {i}"))
                    for i in range(3)]
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline:
                    if backend._pool.healthy_count("prefill") == 2:
                        break
                    await asyncio.sleep(0.05)
                assert backend._pool.healthy_count("prefill") == 2, \
                    backend._pool.stats()
                done = await asyncio.gather(*tasks,
                                            return_exceptions=True)
                errs = [d for d in done if isinstance(d, Exception)]
                assert not errs, f"client-visible failures: {errs}"
                assert all(done)
                stats = await backend.engine_stats()
                pool = stats["disagg"]["pool"]
                asc = pool["autoscale"]
                assert asc["spawns"] >= 1
                assert asc["target"]["prefill"] == 2
                assert any(d["action"] == "spawn"
                           for d in asc["actions"])
                # zero sheds: nothing was re-placed or lost scaling UP
                assert pool["re_placements"] == 0
                assert pool["losses"] == 0
                assert pool["chip_seconds"] > 0
            finally:
                await backend.stop()

        run_async(main())
