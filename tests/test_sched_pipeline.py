"""Overlapped scheduler pipeline (tpu.pipeline_depth): edge semantics.

The pipelined dispatch loop keeps up to `pipeline_depth` decode blocks
in flight on the device and moves detokenize/event-build/delivery onto
a bounded-queue emit worker. These tests pin the seams the overlap
opens:

  - token identity: a real tiny CPU engine must produce byte-identical
    streams (greedy AND seeded sampled) at depth 1 (the pre-pipeline
    double buffer) and depth 2, with zero steady-state recompiles
    between traffic waves (compile_cache_sizes pinned).
  - the dispatch->sync window: a cancel landing while a block is in
    flight discards the block remainder; a slot freed at block N is
    never double-sampled by the already-in-flight block N+1 (the stale
    snapshot check); an inbox deadline expiring under a busy pipeline
    sheds as "expired" without touching active streams.
  - the emit worker: engine-loop death with events still queued fails
    every stream open (no hung client); the bounded queue is the
    backpressure contract — a slow sink stalls the dispatch thread
    instead of letting it run unboundedly ahead.

White-box cases drive scheduler internals on a fake engine (no JAX, no
engine thread) exactly like test_scheduler_emit.py; the threaded cases
start the real loop against a fake device.
"""

import threading
import time

import numpy as np
import pytest

from symmetry_tpu.engine.engine import SamplingParams
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.engine.tokenizer import ByteTokenizer


class FakeEngine:
    """The scheduler-facing engine contract, minus the device."""

    def __init__(self, slots=4, block=4, capacity=4096, buckets=(16, 32)):
        self.max_slots = slots
        self.decode_block = block
        self.slot_capacity = capacity
        self.tokenizer = ByteTokenizer()
        self.prefill_buckets = buckets
        self.dispatches = 0
        self.released: list[int] = []

    def bucket_for(self, n):
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket")

    def prefill_batches_for(self, bucket):
        return (4,)

    def prefill_and_insert(self, slot, ids, sampling):
        return ord("A")

    def prefill_and_insert_many(self, group):
        return [ord("A")] * len(group)

    def decode_steps_dispatch(self):
        self.dispatches += 1
        return np.full((self.decode_block, self.max_slots), ord("b"),
                       dtype=np.int32)

    def release_slot(self, slot):
        self.released.append(slot)

    def slot_length(self, slot):
        return 0


def submit(sched, prompt: bytes, max_new=100, cancelled=None,
           deadline_at=None, emit=None):
    sched.submit(GenRequest(
        prompt_ids=list(prompt), sampling=SamplingParams(),
        max_new_tokens=max_new, emit=emit or (lambda ev: None),
        cancelled=cancelled or (lambda: False), id=prompt.decode(),
        deadline_at=deadline_at))


def events_of(batches, req_id):
    return [ev for batch in batches for req, ev in batch
            if req.id == req_id]


class TestDispatchSyncWindow:
    """Races in the window a pipelined block spends in flight."""

    def test_cancel_between_dispatch_and_sync_discards_block(self):
        """The cancel lands AFTER the block's dispatch snapshot was
        taken and BEFORE its sync: the whole block is discarded, the
        stream finishes "cancelled", the slot frees."""
        eng = FakeEngine(slots=1)
        batches: list = []
        sched = Scheduler(eng, emit_batch=batches.append)
        cancelled: list = []
        submit(sched, b"r0", cancelled=lambda: bool(cancelled))
        sched._admit_new()
        sched._flush_events()
        toks = eng.decode_steps_dispatch()
        snapshot = dict(sched._slots)  # the dispatch point
        cancelled.append(True)         # ...block now in flight
        tokens_before = sched.metrics["tokens"]
        sched._process_pending(
            ("decode_block", toks, snapshot, time.monotonic(), None))
        sched._flush_events()
        (ev,) = events_of(batches[-1:], "r0")
        assert ev.done and ev.finish_reason == "cancelled"
        assert ev.text == "" and ev.token_id is None
        assert sched.metrics["tokens"] == tokens_before
        assert not sched._slots and 0 in eng.released

    def test_freed_slot_never_double_sampled_by_in_flight_block(self):
        """Depth 2's hard invariant: r0 hits EOS in block N while block
        N+1 (dispatched before N synced, same snapshot) is already in
        flight; r1 then takes the freed slot. Block N+1's lane tokens
        for that slot belong to NOBODY — they must be discarded, never
        appended to r0 (done) or leaked into r1 (not in the snapshot)."""
        eng = FakeEngine(slots=1, block=4)
        batches: list = []
        sched = Scheduler(eng, emit_batch=batches.append)
        submit(sched, b"r0")
        sched._admit_new()
        sched._flush_events()
        snapshot = dict(sched._slots)
        toks_n = eng.decode_steps_dispatch()
        toks_n[1, 0] = ByteTokenizer.EOS  # r0 stops mid-block N
        toks_n1 = eng.decode_steps_dispatch()  # N+1, in flight behind N
        sched._process_pending(
            ("decode_block", toks_n, snapshot, time.monotonic(), None))
        sched._flush_events()
        (ev,) = events_of(batches[-1:], "r0")
        assert ev.done and ev.finish_reason == "stop" and ev.text == "b"
        # The freed slot is re-admitted before block N+1 syncs.
        submit(sched, b"r1")
        sched._admit_new()
        sched._flush_events()
        assert 0 in sched._slots and sched._slots[0].req.id == "r1"
        tokens_before = sched.metrics["tokens"]
        n_batches = len(batches)
        sched._process_pending(
            ("decode_block", toks_n1, snapshot, time.monotonic(), None))
        sched._flush_events()
        # Stale lane discarded wholesale: no event for anyone, no tokens
        # booked, r1's stream untouched by a block dispatched before it
        # existed.
        assert len(batches) == n_batches
        assert sched.metrics["tokens"] == tokens_before
        assert not events_of(batches[n_batches:], "r1")
        assert sched._slots[0].req.id == "r1"

    def test_deadline_expires_while_pipeline_busy_sheds_expired(self):
        """A queued request whose deadline passes while blocks are in
        flight is shed at its admission pass with finish "expired" —
        active streams never see it occupy a slot."""
        eng = FakeEngine(slots=2)
        batches: list = []
        sched = Scheduler(eng, emit_batch=batches.append)
        submit(sched, b"r0")
        sched._admit_new()
        sched._flush_events()
        submit(sched, b"late", deadline_at=time.monotonic() - 0.01)
        sched._admit_new()
        sched._flush_events()
        (ev,) = events_of(batches, "late")
        assert ev.done and ev.finish_reason == "expired"
        assert ev.error and "deadline" in ev.error
        # Only r0 ever held the slot.
        assert len(sched._slots) == 1
        assert sched._slots[0].req.id == "r0"


class TestEmitWorkerFaults:
    def test_loop_death_with_queued_events_fails_streams_open(self):
        """The engine loop dies mid-traffic with the emit queue
        non-empty (slow sink): every open stream must still receive a
        terminal error event — the worker drains before shutdown, no
        client hangs."""

        class DyingEngine(FakeEngine):
            def decode_steps_dispatch(self):
                if self.dispatches >= 3:
                    raise RuntimeError("device lost")
                return super().decode_steps_dispatch()

        eng = DyingEngine(slots=2, block=4)
        done = {"r0": threading.Event(), "r1": threading.Event()}
        finals: dict[str, object] = {}

        def sink(batch):
            time.sleep(0.05)  # keep the emit queue non-empty at death
            for req, ev in batch:
                if ev.done:
                    finals[req.id] = ev
                    done[req.id].set()

        sched = Scheduler(eng, pipeline_depth=2, emit_queue_blocks=2,
                          emit_batch=sink)
        submit(sched, b"r0", max_new=1000)
        submit(sched, b"r1", max_new=1000)
        sched.start()
        for rid, ev in done.items():
            assert ev.wait(30), f"{rid} hung after engine death"
        for rid, ev in finals.items():
            assert ev.finish_reason == "error", (rid, ev)
            assert "device lost" in (ev.error or ""), (rid, ev)
        sched._thread.join(10)
        assert not sched._thread.is_alive()
        sched._emit_thread.join(10)
        assert not sched._emit_thread.is_alive()

    def test_bounded_queue_backpressures_dispatch_thread(self):
        """emit_queue_blocks=1 + a slow sink: the dispatch thread must
        STALL on the full queue rather than run unboundedly ahead —
        dispatched-but-undelivered blocks stay within the pipeline
        depth + the queue bound + the in-progress batch, and the stream
        arrives complete and in order anyway."""
        eng = FakeEngine(slots=1, block=4)
        lead: list[int] = []
        sink_calls = [0]
        batches: list = []

        def sink(batch):
            time.sleep(0.02)
            lead.append(eng.dispatches - sink_calls[0])
            sink_calls[0] += 1
            batches.append(list(batch))

        sched = Scheduler(eng, pipeline_depth=2, emit_queue_blocks=1,
                          emit_batch=sink)
        done = threading.Event()
        submit(sched, b"r0", max_new=121,
               emit=lambda ev: done.set() if ev.done else None)
        sched.start()
        # The done event reaches the sink too (emit_batch delivery);
        # poll the collected batches for it.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(ev.done for ev in events_of(batches, "r0")):
                break
            time.sleep(0.01)
        sched.stop()
        evs = events_of(batches, "r0")
        assert evs and evs[-1].done and evs[-1].finish_reason == "length"
        # Completeness + order under backpressure: 1 activation token +
        # 120 block tokens, in production order.
        assert "".join(ev.text for ev in evs) == "A" + "b" * 120
        gens = [ev.tokens_generated for ev in evs]
        assert gens == sorted(gens) and gens[-1] == 121
        # The backpressure bound: in-flight on device (<= depth) +
        # queued (<= emit_queue_blocks) + the batch being delivered +
        # the engine thread's current block buffer.
        assert max(lead) <= 2 + 1 + 2, f"dispatch ran ahead: {max(lead)}"


class TestDepthTokenIdentity:
    """Real tiny CPU engine: the A/B invariant the tentpole pins."""

    @pytest.fixture(scope="class")
    def setup(self):
        import jax
        import jax.numpy as jnp

        from symmetry_tpu.models import init_params, preset

        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        return cfg, params

    def _run_depth(self, cfg, params, depth):
        import jax.numpy as jnp

        from symmetry_tpu.engine.engine import InferenceEngine

        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=96,
            prefill_buckets=(16, 48), cache_dtype=jnp.float32,
            decode_block=4)
        sched = Scheduler(engine, debug_invariants=True,
                          pipeline_depth=depth)
        reqs = [
            (list(b"pipeline greedy one"), SamplingParams(), 16),
            (list(b"greedy two"), SamplingParams(), 16),
            (list(b"seeded sampled"),
             SamplingParams(temperature=0.8, top_k=8, seed=7), 16),
        ]
        sched.start()
        sigs = []
        try:
            for wave in range(2):
                results = {i: [] for i in range(len(reqs))}
                done = {i: threading.Event() for i in range(len(reqs))}
                for i, (ids, sampling, max_new) in enumerate(reqs):
                    def emit(ev, i=i):
                        results[i].append(ev)
                        if ev.done:
                            done[i].set()
                    sched.submit(GenRequest(
                        prompt_ids=list(ids), sampling=sampling,
                        max_new_tokens=max_new, emit=emit,
                        id=f"w{wave}r{i}"))
                for i, ev in done.items():
                    assert ev.wait(120), f"depth {depth} r{i} hung"
                sigs.append({
                    i: ("".join(ev.text for ev in evs),
                        [ev.token_id for ev in evs
                         if ev.token_id is not None],
                        evs[-1].tokens_generated,
                        evs[-1].finish_reason)
                    for i, evs in results.items()})
                if wave == 0:
                    sizes_w1 = engine.compile_cache_sizes()
        finally:
            sched.stop()
        # Zero steady-state recompiles: wave 2 re-ran the same traffic
        # shapes and must not have grown any jit cache.
        assert engine.compile_cache_sizes() == sizes_w1
        stats = sched.stats()
        assert stats["pipeline_depth"] == depth
        return sigs, stats

    def test_identity_and_split_depth_1_vs_2(self, setup):
        cfg, params = setup
        sigs1, stats1 = self._run_depth(cfg, params, 1)
        sigs2, stats2 = self._run_depth(cfg, params, 2)
        assert sigs1 == sigs2
        # The emit split: depth 1 keeps the inline pre-pipeline path
        # (zero offloaded wall), depth 2's worker carried real work.
        assert stats1["offloaded_s"] == 0
        assert stats2["offloaded_s"] > 0
        for stats in (stats1, stats2):
            assert stats["dispatch_thread_s"] > 0
            assert stats["dispatch_thread_block_s"]["p50"] is not None
            assert "pipeline_live_depth" in stats
            assert "emit_queue_depth" in stats
