# Installer parity with the reference's install.ps1 (installs the CLI and
# scaffolds a default provider config; reference install.ps1:1-58). Windows
# counterpart of install.sh: pip-installs this checkout and writes a
# tpu_native default provider.yaml under the user's config directory.

$ErrorActionPreference = "Stop"

$ConfigDir = if ($env:SYMMETRY_CONFIG_DIR) { $env:SYMMETRY_CONFIG_DIR }
             else { Join-Path $env:USERPROFILE ".config\symmetry" }
$ConfigPath = Join-Path $ConfigDir "provider.yaml"
$RepoDir = Split-Path -Parent $MyInvocation.MyCommand.Path

Write-Host "Installing symmetry-tpu from $RepoDir ..."
python -m pip install --user $RepoDir

New-Item -ItemType Directory -Force -Path $ConfigDir | Out-Null

if (Test-Path $ConfigPath) {
    Write-Host "Config already exists at $ConfigPath - leaving it untouched."
} else {
    # Prompt only when interactive (mirrors install.sh's `[ -t 0 ]` branch);
    # CI/non-interactive installs take the defaults instead of hanging on
    # Read-Host. SYMMETRY_NONINTERACTIVE=1 forces the non-prompting path.
    $DefaultName = "$env:USERNAME-tpu"
    $Name = $DefaultName
    $Model = "llama3-8b"
    $ServerKey = ""
    # IsInputRedirected is the stdin-state check ([Environment]::UserInteractive
    # only detects services, and is $true in CI shells and -NonInteractive).
    $Interactive = [Environment]::UserInteractive -and
                   -not [Console]::IsInputRedirected -and
                   -not $env:SYMMETRY_NONINTERACTIVE
    if ($Interactive) {
        $Name = Read-Host "Provider name [$DefaultName]"
        if (-not $Name) { $Name = $DefaultName }
        $Model = Read-Host "Model preset [llama3-8b]"
        if (-not $Model) { $Model = "llama3-8b" }
        $ServerKey = Read-Host "Server key (hex, empty for private provider)"
    }

    $Public = "true"
    if (-not $ServerKey) {
        $Public = "false"
        $ServerKey = "0" * 64
    }

    @"
# symmetry-tpu provider config (see README.md; field parity with the
# reference provider.yaml plus the tpu: engine section)
name: $Name
public: $Public
serverKey: "$ServerKey"
modelName: "$Model"
apiProvider: tpu_native
dataCollectionEnabled: false
maxConnections: 16
path: $($ConfigDir -replace '\\', '/')
tpu:
  model_preset: $Model
  dtype: bfloat16
  quantization: int8
  kv_quantization: int8
  max_batch_size: 16
  max_seq_len: 2048
  prefill_buckets: [128, 512, 2048]
  decode_block: 16
  # checkpoint_path: /path/to/hf/safetensors/dir
  # tokenizer_path: /path/to/tokenizer.json
"@ | Set-Content -Path $ConfigPath -Encoding UTF8
    Write-Host "Wrote default config to $ConfigPath"
}

Write-Host ""
Write-Host "Run the provider with:  symmetry-tpu-provider -c $ConfigPath"
Write-Host "Run a server with:      symmetry-tpu-server"
